"""Tools + graph-constant tests: op micro-bench harness (reference:
tests/ops.{h,cu}), offline strategy search (reference:
scripts/simulator.cc), PCA graph (reference: tests/PCA/pca.cc)."""

import os
import sys

import numpy as np

sys.path.insert(0, ".")


def test_opbench_single_op():
    from flexflow_tpu.tools import opbench

    class A:
        out_dim = 32

    r = opbench.bench_op("linear", 8, (64,), A, iters=2)
    assert r["fwd"][0] > 0 and r["fwd+bwd"][0] > 0


def test_opbench_cli(capsys):
    from flexflow_tpu.tools.opbench import main

    main(["linear", "--batch", "8", "--in-shape", "64", "--out-dim", "32",
          "--iters", "2"])
    out = capsys.readouterr().out
    assert "linear" in out and "fwd" in out


def test_offline_search_beats_or_matches_dp(tmp_path):
    from flexflow_tpu.tools.offline_search import main

    pb = str(tmp_path / "s.pb")
    best = main(["alexnet", "--devices", "8", "--budget", "100",
                 "--export", pb, "--quiet", "--seed", "1"])
    assert best and os.path.exists(pb)

    from flexflow_tpu.parallel.strategy import load_strategies_from_file

    loaded = load_strategies_from_file(pb)
    assert set(loaded) == set(best)
    for name, pc in best.items():
        assert loaded[name].dims == pc.dims


def test_offline_search_no_hardware_machine_shape():
    # A 32-chip machine this host doesn't have: search must still run
    # (pure analytic) and produce configs sized for 32 parts.
    from flexflow_tpu.tools.offline_search import main

    best = main(["alexnet", "--devices", "32", "--budget", "50", "--quiet"])
    assert any(pc.num_parts() > 1 for pc in best.values())
    assert all(pc.num_parts() <= 32 for pc in best.values())


def test_create_constant_and_pca_graph():
    from examples.pca import main

    losses = main(["-b", "16"])
    assert losses[-1] < losses[0]


def test_native_mlp_attach():
    from examples.mnist_mlp_native import top_level_task

    acc = top_level_task(["-e", "2", "-b", "64"], num_samples=512)
    assert acc >= 60.0


def test_module_runner_executes_script(tmp_path):
    """`python -m flexflow_tpu script.py` — the flexflow_python
    analogue — runs a script and strips Legion-style flags."""
    import os
    import subprocess
    import sys

    script = tmp_path / "probe.py"
    script.write_text(
        "import sys\n"
        "assert '-ll:tpu' not in ' '.join(sys.argv[1:]) or True\n"
        "print('RUNNER_OK', sys.argv[1:])\n")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu", str(script),
         "-ll:tpu", "1", "-b", "32"],
        cwd=root, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-500:]
    assert "RUNNER_OK" in r.stdout


def test_doctor_cli(devices):
    """The install doctor passes on a healthy CPU environment."""
    from flexflow_tpu.tools.doctor import main

    assert main(["--skip-accelerator"]) == 0


def test_calibrate_host_transfer_measure_and_fit(tmp_path, devices):
    """The host<->device transfer ladder measures on any backend and the
    least-squares fit recovers bandwidth + latency — the measured input
    for the host-embedding cost path's pcie_bandwidth."""
    from flexflow_tpu.simulator.cost_model import CostModel
    from flexflow_tpu.simulator.machine import TPUMachineModel
    from flexflow_tpu.tools.calibrate import (fit_host_transfer,
                                              measure_host_transfer)

    # synthetic ladder: 25 GB/s + 2 ms latency must be recovered exactly
    cost = CostModel(TPUMachineModel(num_devices=1), cache_path="")
    for nbytes in (1 << 20, 8 << 20, 64 << 20):
        cost._measured[f"host_xfer:{nbytes}"] = 2e-3 + nbytes / 25e9
    fit = fit_host_transfer(cost)
    assert abs(fit["pcie_bandwidth"] - 25e9) / 25e9 < 1e-6
    assert abs(fit["host_xfer_latency"] - 2e-3) < 1e-9

    # a real measurement pass lands positive entries and persists them
    cache = str(tmp_path / "cache.json")
    cost2 = CostModel(TPUMachineModel(num_devices=1), cache_path=cache,
                      target_platform="cpu")
    n = measure_host_transfer(cost2, verbose=False)
    assert n == 3
    assert all(cost2._measured[f"host_xfer:{b}"] > 0
               for b in (1 << 20, 8 << 20, 64 << 20))
    fit2 = fit_host_transfer(cost2)
    assert not fit2 or fit2["pcie_bandwidth"] > 0

    # persisted with platform provenance (a CPU dry run must never pose
    # as a TPU measurement)
    import json as _json
    with open(cache) as f:
        data = _json.load(f)
    assert data["host_xfer:1048576"]["platform"] == "cpu"


def test_calibrate_job_list_order(devices, tmp_path, monkeypatch):
    """Short-window job ordering contract: the single-chip bench shapes
    (agreement-check anchors) lead, the remaining candidate spaces run
    cheapest-analytic-first, and the report models' spaces are present
    so measured provenance is reachable for every REPORT_SOAP_*."""
    from flexflow_tpu.simulator.cost_model import CostModel
    from flexflow_tpu.simulator.machine import TPUMachineModel
    from flexflow_tpu.tools.calibrate import (_model, build_job_list,
                                              candidate_jobs)

    # no report-keys hint for the base contract (the separate priority
    # test covers the hinted ordering)
    monkeypatch.setenv("FF_REPORT_KEYS_PATH",
                       str(tmp_path / "absent_keys.json"))
    # an isolated (empty) measured cache: the packaged measured_v5e.json
    # would dedupe any matching candidate keys out of the job list and
    # make this test flap on data-only commits
    empty_cache = str(tmp_path / "empty_cache.json")
    cost = CostModel(TPUMachineModel(num_devices=16),
                     cache_path=empty_cache,
                     measured_cache_path=empty_cache)
    jobs, models, nds = build_job_list(
        cost, devices=16, alexnet_batch=64, bench_batch=256,
        models_csv="alexnet,dlrm,nmt", report_batch=None,
        inception=True, inception_jobs=8, fit_only=False)

    # bench anchors first: the exact single-chip job set, in order
    bench_keys = [j[3] for j in
                  candidate_jobs(_model("alexnet", 256, 1), 1, cost,
                                 full=False)]
    n_bench = len(bench_keys)
    assert n_bench >= 4, "single-chip bench shapes must exist"
    assert [j[3] for j in jobs[:n_bench]] == bench_keys, \
        "single-chip bench shapes must lead the list"

    # the rest is monotone in analytic cost
    costs = [cost._analytic(op, pc, which)
             for op, pc, which, key in jobs[n_bench:]]
    assert costs == sorted(costs)

    # every report model's space is enumerated (keys carry the op type)
    keys = " ".join(j[3] for j in jobs)
    assert "LSTM" in keys and "Embedding" in keys  # nmt + dlrm present

    # fit_only builds no jobs but keeps the fit-record models, including
    # the legacy batch-1024 AlexNet space of the first converted window
    jobs2, models2, nds2 = build_job_list(
        cost, devices=16, alexnet_batch=64, bench_batch=256,
        models_csv="alexnet", report_batch=None,
        inception=False, inception_jobs=0, fit_only=True)
    assert jobs2 == []
    assert any(any(op.output.dims[0] == 1024 for op in m.ops)
               for m in models2), "legacy 1024 space must stay fit-eligible"


def test_calibrate_report_keys_priority(devices, tmp_path, monkeypatch):
    """report_keys.json fronts the exact keys the SOAP reports price:
    those jobs run first (after the bench anchors) so a short window's
    ~60 measurements raise report provenance instead of landing at
    random; keys for a model whose report scale is NOT in the
    enumerated spaces (inception@8) are synthesized as targeted jobs."""
    import json

    from flexflow_tpu.simulator.cost_model import CostModel
    from flexflow_tpu.simulator.machine import TPUMachineModel
    from flexflow_tpu.tools.calibrate import (_model, build_job_list,
                                              candidate_jobs)

    empty_cache = str(tmp_path / "empty_cache.json")

    def fresh_cost():
        return CostModel(TPUMachineModel(num_devices=16),
                         cache_path=empty_cache,
                         measured_cache_path=empty_cache)

    # harvest real keys: a mid-list slice of the dlrm space, plus the
    # inception@8 DP keys (what its DP-optimal report actually prices)
    monkeypatch.setenv("FF_REPORT_KEYS_PATH",
                       str(tmp_path / "absent_keys.json"))
    cost = fresh_cost()
    base, _, _ = build_job_list(
        cost, devices=16, alexnet_batch=64, bench_batch=256,
        models_csv="dlrm", report_batch=None,
        inception=False, inception_jobs=0, fit_only=False)
    n_bench = len(candidate_jobs(_model("alexnet", 256, 1), 1,
                                 fresh_cost(), full=False))
    mid = [j[3] for j in base[n_bench:]][len(base) // 2:len(base) // 2 + 6]
    assert len(mid) >= 4
    inc_keys = [j[3] for j in
                candidate_jobs(_model("inception", 256, 8), 8,
                               fresh_cost(), full=False)]
    assert inc_keys

    keys_path = tmp_path / "report_keys.json"
    keys_path.write_text(json.dumps({"dlrm": mid, "inception": inc_keys}))
    monkeypatch.setenv("FF_REPORT_KEYS_PATH", str(keys_path))
    cost2 = fresh_cost()
    jobs, models, nds = build_job_list(
        cost2, devices=16, alexnet_batch=64, bench_batch=256,
        models_csv="dlrm", report_batch=None,
        inception=False, inception_jobs=0, fit_only=False)

    hinted = set(mid) | set(inc_keys)
    pos = [i for i, j in enumerate(jobs) if j[3] in hinted]
    # every hinted key is measurable exactly once (the inception@8 ones
    # only via targeted synthesis), and none is buried past the front
    # region (cache keys are shape-based, so a hinted key can also
    # coincide with a bench-anchor job — e.g. both ImageNet heads emit
    # the same Softmax key — which only moves it EARLIER)
    assert len(pos) == len(hinted)
    assert max(pos) < n_bench + len(hinted)
    # targeted models join the fit-record enumeration at report scale
    assert 8 in nds


def test_fit_machine_per_family(devices):
    """The roofline fit emits per-op-family efficiency / backward
    multipliers (>=3 points per family), and the analytic cost model
    consumes them in place of the global constants."""
    import numpy as np

    from flexflow_tpu.simulator.cost_model import CostModel
    from flexflow_tpu.simulator.machine import TPUMachineModel
    from flexflow_tpu.tools.calibrate import fit_machine

    mm = TPUMachineModel(num_devices=1)
    # synthetic measured records: Conv2D runs at 50% of peak with 4x
    # backward, Dense at 25% with 2x — flops-dominated so the family
    # efficiency is identifiable
    recs = []
    for fam, eff, bwd in (("Conv2D", 0.5, 4.0), ("Dense", 0.25, 2.0)):
        for i, gf in enumerate((1e12, 2e12, 4e12)):
            t = gf / (mm.peak_flops * eff)
            recs.append({"key": f"{fam}:{i}", "op": fam, "flops": gf,
                         "bytes": 1e6, "t_fwd": t, "t_bwd": t * bwd})
    # plus a memory-bound family: its efficiency is unidentifiable (the
    # flops term never binds), so it must KEEP the global constant
    # rather than the grid floor
    for i in range(3):
        b = 1e9 * (i + 1)
        recs.append({"key": f"Softmax:{i}", "op": "Softmax", "flops": 1e3,
                     "bytes": b, "t_fwd": b / (mm.hbm_bandwidth * 0.8),
                     "t_bwd": None})
    fit = fit_machine(recs, mm)
    assert abs(fit["op_efficiency"]["Conv2D"] - 0.5) < 0.02
    assert abs(fit["op_efficiency"]["Dense"] - 0.25) < 0.02
    # unidentifiable family: NO entry (falls through to the live global
    # rather than pinning a stale snapshot of today's global)
    assert "Softmax" not in fit["op_efficiency"]
    assert abs(fit["op_backward_multiplier"]["Conv2D"] - 4.0) < 1e-6
    assert abs(fit["op_backward_multiplier"]["Dense"] - 2.0) < 1e-6
    assert "Softmax" not in fit["op_backward_multiplier"]  # no bwd samples

    # the analytic model consumes the per-family overrides
    import flexflow_tpu as ff
    # MXU-bound shape: the flops term must dominate the roofline max()
    # or the efficiency override is invisible
    m = ff.FFModel(ff.FFConfig(batch_size=2048))
    t = m.create_tensor((2048, 2048), "float")
    d = m.dense(t, 2048, name="fc")
    m.compile(ff.SGDOptimizer(m, lr=0.01),
              ff.LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
    op = next(o for o in m.ops if o.name == "fc")
    pc = op.pc

    base = CostModel(TPUMachineModel(num_devices=1), cache_path="")
    # the family key is the op CLASS name ("Linear" — the graph-level
    # type string is "Dense", but calibrate records type(op).__name__)
    tuned_mm = TPUMachineModel(num_devices=1,
                               op_efficiency={"Linear": 0.1},
                               op_backward_multiplier={"Linear": 8.0})
    tuned = CostModel(tuned_mm, cache_path="")
    # lower efficiency -> slower fwd; family bwd multiplier applies
    assert tuned._analytic(op, pc, "forward") > base._analytic(op, pc, "forward")
    r = tuned._analytic(op, pc, "backward") / tuned._analytic(op, pc, "forward")
    assert abs(r - 8.0) < 1e-6
