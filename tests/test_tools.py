"""Tools + graph-constant tests: op micro-bench harness (reference:
tests/ops.{h,cu}), offline strategy search (reference:
scripts/simulator.cc), PCA graph (reference: tests/PCA/pca.cc)."""

import os
import sys

import numpy as np

sys.path.insert(0, ".")


def test_opbench_single_op():
    from flexflow_tpu.tools import opbench

    class A:
        out_dim = 32

    r = opbench.bench_op("linear", 8, (64,), A, iters=2)
    assert r["fwd"][0] > 0 and r["fwd+bwd"][0] > 0


def test_opbench_cli(capsys):
    from flexflow_tpu.tools.opbench import main

    main(["linear", "--batch", "8", "--in-shape", "64", "--out-dim", "32",
          "--iters", "2"])
    out = capsys.readouterr().out
    assert "linear" in out and "fwd" in out


def test_offline_search_beats_or_matches_dp(tmp_path):
    from flexflow_tpu.tools.offline_search import main

    pb = str(tmp_path / "s.pb")
    best = main(["alexnet", "--devices", "8", "--budget", "100",
                 "--export", pb, "--quiet", "--seed", "1"])
    assert best and os.path.exists(pb)

    from flexflow_tpu.parallel.strategy import load_strategies_from_file

    loaded = load_strategies_from_file(pb)
    assert set(loaded) == set(best)
    for name, pc in best.items():
        assert loaded[name].dims == pc.dims


def test_offline_search_no_hardware_machine_shape():
    # A 32-chip machine this host doesn't have: search must still run
    # (pure analytic) and produce configs sized for 32 parts.
    from flexflow_tpu.tools.offline_search import main

    best = main(["alexnet", "--devices", "32", "--budget", "50", "--quiet"])
    assert any(pc.num_parts() > 1 for pc in best.values())
    assert all(pc.num_parts() <= 32 for pc in best.values())


def test_create_constant_and_pca_graph():
    from examples.pca import main

    losses = main(["-b", "16"])
    assert losses[-1] < losses[0]


def test_native_mlp_attach():
    from examples.mnist_mlp_native import top_level_task

    acc = top_level_task(["-e", "2", "-b", "64"], num_samples=512)
    assert acc >= 60.0


def test_module_runner_executes_script(tmp_path):
    """`python -m flexflow_tpu script.py` — the flexflow_python
    analogue — runs a script and strips Legion-style flags."""
    import os
    import subprocess
    import sys

    script = tmp_path / "probe.py"
    script.write_text(
        "import sys\n"
        "assert '-ll:tpu' not in ' '.join(sys.argv[1:]) or True\n"
        "print('RUNNER_OK', sys.argv[1:])\n")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu", str(script),
         "-ll:tpu", "1", "-b", "32"],
        cwd=root, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-500:]
    assert "RUNNER_OK" in r.stdout


def test_doctor_cli(devices):
    """The install doctor passes on a healthy CPU environment."""
    from flexflow_tpu.tools.doctor import main

    assert main(["--skip-accelerator"]) == 0
