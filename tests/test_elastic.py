"""Elastic training: auto-resume and hang detection (runtime/elastic.py).

Beyond the reference (fail-stop, no checkpointing — SURVEY §5.3/5.4):
a resumed run must be numerically identical to an uninterrupted one,
and a wedged device must surface as DeviceHangError instead of an
infinite block.
"""

import json
import threading
import time

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.observability import events
from flexflow_tpu.runtime.elastic import (DeviceHangError, StepWatchdog,
                                          elastic_train)
from flexflow_tpu.runtime.resilience import ResumeMismatchError


def _build(opt="adam", n_samples=48):
    cfg = ff.FFConfig(batch_size=16)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False, name="input")
    t = m.dense(inp, 16, activation="relu", name="fc1")
    t = m.dense(t, 4, name="fc2")
    m.softmax(t, name="sm")
    optimizer = (ff.AdamOptimizer(alpha=0.01) if opt == "adam"
                 else ff.SGDOptimizer(lr=0.1, momentum=0.9))
    m.compile(optimizer, "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=9)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((n_samples, 8), dtype=np.float32)
    y = rng.integers(0, 4, size=(n_samples, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y, seed=5)
    return m, dl


@pytest.mark.parametrize("opt", ["adam", "sgd"])
def test_resume_matches_uninterrupted(tmp_path, devices, opt):
    """2 epochs + restart + 2 more == 4 straight epochs, bitwise-close
    (same shuffle stream, same per-step RNG, same Adam schedule)."""
    ck1 = str(tmp_path / "ck_interrupted")
    m1, dl1 = _build(opt)
    ran = elastic_train(m1, dl1, epochs=2, checkpoint_dir=ck1)
    assert ran == 2
    # "process restart": fresh model + loader, same checkpoint dir
    m2, dl2 = _build(opt)
    ran = elastic_train(m2, dl2, epochs=4, checkpoint_dir=ck1)
    assert ran == 2  # only the remaining epochs execute

    m3, dl3 = _build(opt)
    ran = elastic_train(m3, dl3, epochs=4,
                        checkpoint_dir=str(tmp_path / "ck_straight"))
    assert ran == 4
    np.testing.assert_allclose(m2.get_parameter("fc1", "kernel"),
                               m3.get_parameter("fc1", "kernel"),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(m2.get_parameter("fc2", "kernel"),
                               m3.get_parameter("fc2", "kernel"),
                               rtol=1e-6, atol=1e-7)


def test_failure_saves_then_propagates(tmp_path, devices):
    """An exception mid-training still leaves a usable checkpoint."""
    m, dl = _build()
    boom = RuntimeError("injected failure")

    def on_epoch(epoch, metrics):
        if epoch == 1:
            raise boom

    with pytest.raises(RuntimeError, match="injected failure"):
        elastic_train(m, dl, epochs=4, checkpoint_dir=str(tmp_path / "ck"),
                      on_epoch=on_epoch)
    m2, dl2 = _build()
    ran = elastic_train(m2, dl2, epochs=4,
                        checkpoint_dir=str(tmp_path / "ck"))
    assert 0 < ran < 4  # resumed from the mid-failure save


def test_step_granular_resume_mid_epoch(tmp_path, devices):
    """A failure between mid-epoch saves resumes at the exact STEP (not
    the epoch boundary) and continues bitwise-identically."""
    mb, dlb = _build()
    elastic_train(mb, dlb, epochs=2, checkpoint_dir=str(tmp_path / "base"))
    base = np.asarray(mb.get_parameter("fc1", "kernel"))

    m, dl = _build()
    boom = RuntimeError("mid-epoch crash")
    calls = {"n": 0}

    real_next = type(dl).next_batch

    def crashing_next(self, ff_=None):
        calls["n"] += 1
        if calls["n"] == 5:  # step 4: one step into epoch 2
            raise boom
        return real_next(self, ff_)

    dl.next_batch = crashing_next.__get__(dl)
    with pytest.raises(RuntimeError, match="mid-epoch crash"):
        elastic_train(m, dl, epochs=2, checkpoint_dir=str(tmp_path / "ck"),
                      save_every_steps=1)

    m2, dl2 = _build()
    ran = elastic_train(m2, dl2, epochs=2,
                        checkpoint_dir=str(tmp_path / "ck"),
                        save_every_steps=1)
    assert ran == 1  # only the interrupted epoch re-enters the loop
    assert m2._step_count == 6
    got = np.asarray(m2.get_parameter("fc1", "kernel"))
    assert (got == base).all()  # bitwise, not just allclose


def test_resume_mismatch_named_error_and_recompute(tmp_path, devices):
    m, dl = _build()
    elastic_train(m, dl, epochs=1, checkpoint_dir=str(tmp_path / "ck"))

    # dataset grew: 48 -> 64 samples = 3 -> 4 steps/epoch
    m2, dl2 = _build(n_samples=64)
    with pytest.raises(ResumeMismatchError, match="3 steps/epoch"):
        elastic_train(m2, dl2, epochs=2, checkpoint_dir=str(tmp_path / "ck"))

    m3, dl3 = _build(n_samples=64)
    with pytest.warns(RuntimeWarning, match="recomputing"):
        ran = elastic_train(m3, dl3, epochs=2,
                            checkpoint_dir=str(tmp_path / "ck"),
                            on_steps_mismatch="recompute")
    assert ran > 0


def test_watchdog_detects_hang():
    wd = StepWatchdog(timeout=0.3)
    t0 = time.perf_counter()
    with pytest.raises(DeviceHangError):
        wd.run(time.sleep, 5.0)  # stands in for a blocked device_get
    assert time.perf_counter() - t0 < 2.0  # caller regained control fast


def test_watchdog_passes_through_results_and_errors():
    wd = StepWatchdog(timeout=5.0)
    assert wd.run(lambda: 42) == 42
    with pytest.raises(ValueError):
        wd.run(lambda: (_ for _ in ()).throw(ValueError("x")))


def test_watchdog_names_threads_and_narrates_hangs(tmp_path, monkeypatch):
    """Stranded workers carry ff-watchdog-* names, a device_hang event
    lands in the trace before the raise, and accumulated hangs warn."""
    trace = tmp_path / "trace.jsonl"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    events.reset_active()
    StepWatchdog._stranded.clear()
    release = threading.Event()
    try:
        wd = StepWatchdog(timeout=0.05)
        with pytest.raises(DeviceHangError, match="ff-watchdog-"):
            wd.run(release.wait)
        stranded = [t for t in threading.enumerate()
                    if t.name.startswith("ff-watchdog-")]
        assert stranded  # the worker is pinned, and identifiable by name
        # two more hangs push past the stranded-thread warning threshold
        with pytest.warns(RuntimeWarning, match="stranded"):
            for _ in range(StepWatchdog.STRANDED_WARN_AT - 1):
                with pytest.raises(DeviceHangError):
                    wd.run(release.wait)
    finally:
        release.set()  # unpin the workers
        StepWatchdog._stranded.clear()
        events.reset_active()
    names = [json.loads(l).get("name") for l in open(trace) if l.strip()]
    assert names.count("device_hang") == StepWatchdog.STRANDED_WARN_AT
