"""Elastic training: auto-resume and hang detection (runtime/elastic.py).

Beyond the reference (fail-stop, no checkpointing — SURVEY §5.3/5.4):
a resumed run must be numerically identical to an uninterrupted one,
and a wedged device must surface as DeviceHangError instead of an
infinite block.
"""

import time

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.runtime.elastic import (DeviceHangError, StepWatchdog,
                                          elastic_train)


def _build(opt="adam"):
    cfg = ff.FFConfig(batch_size=16)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False, name="input")
    t = m.dense(inp, 16, activation="relu", name="fc1")
    t = m.dense(t, 4, name="fc2")
    m.softmax(t, name="sm")
    optimizer = (ff.AdamOptimizer(alpha=0.01) if opt == "adam"
                 else ff.SGDOptimizer(lr=0.1, momentum=0.9))
    m.compile(optimizer, "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=9)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((48, 8), dtype=np.float32)
    y = rng.integers(0, 4, size=(48, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y, seed=5)
    return m, dl


@pytest.mark.parametrize("opt", ["adam", "sgd"])
def test_resume_matches_uninterrupted(tmp_path, devices, opt):
    """2 epochs + restart + 2 more == 4 straight epochs, bitwise-close
    (same shuffle stream, same per-step RNG, same Adam schedule)."""
    ck1 = str(tmp_path / "ck_interrupted")
    m1, dl1 = _build(opt)
    ran = elastic_train(m1, dl1, epochs=2, checkpoint_dir=ck1)
    assert ran == 2
    # "process restart": fresh model + loader, same checkpoint dir
    m2, dl2 = _build(opt)
    ran = elastic_train(m2, dl2, epochs=4, checkpoint_dir=ck1)
    assert ran == 2  # only the remaining epochs execute

    m3, dl3 = _build(opt)
    ran = elastic_train(m3, dl3, epochs=4,
                        checkpoint_dir=str(tmp_path / "ck_straight"))
    assert ran == 4
    np.testing.assert_allclose(m2.get_parameter("fc1", "kernel"),
                               m3.get_parameter("fc1", "kernel"),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(m2.get_parameter("fc2", "kernel"),
                               m3.get_parameter("fc2", "kernel"),
                               rtol=1e-6, atol=1e-7)


def test_failure_saves_then_propagates(tmp_path, devices):
    """An exception mid-training still leaves a usable checkpoint."""
    m, dl = _build()
    boom = RuntimeError("injected failure")

    def on_epoch(epoch, metrics):
        if epoch == 1:
            raise boom

    with pytest.raises(RuntimeError, match="injected failure"):
        elastic_train(m, dl, epochs=4, checkpoint_dir=str(tmp_path / "ck"),
                      on_epoch=on_epoch)
    m2, dl2 = _build()
    ran = elastic_train(m2, dl2, epochs=4,
                        checkpoint_dir=str(tmp_path / "ck"))
    assert 0 < ran < 4  # resumed from the mid-failure save


def test_watchdog_detects_hang():
    wd = StepWatchdog(timeout=0.3)
    t0 = time.perf_counter()
    with pytest.raises(DeviceHangError):
        wd.run(time.sleep, 5.0)  # stands in for a blocked device_get
    assert time.perf_counter() - t0 < 2.0  # caller regained control fast


def test_watchdog_passes_through_results_and_errors():
    wd = StepWatchdog(timeout=5.0)
    assert wd.run(lambda: 42) == 42
    with pytest.raises(ValueError):
        wd.run(lambda: (_ for _ in ()).throw(ValueError("x")))
