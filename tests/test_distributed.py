"""Multi-host backend: hybrid ICI×DCN mesh + per-host batch feeding.

Single-process tests on the virtual 8-device mesh: the DCN axis must cut
on (simulated) node boundaries, batch-dim sharding must land on DCN
first, and a model compiled with --nodes 2 must train over the hybrid
mesh exactly like the flat one.
"""

import numpy as np
import pytest

import jax

import flexflow_tpu as ff
from flexflow_tpu.parallel import distributed as dist
from flexflow_tpu.parallel.mesh import Machine


def test_hybrid_machine_axes(devices):
    m = dist.hybrid_machine(dcn_degree=2, devices=devices)
    assert m.axis_names[0] == "dcn"
    assert m.axis_sizes == (2, 2, 2)
    assert m.num_devices == 8
    # Batch degree 8 spans dcn first, then ICI axes.
    groups = m.axes_for_degrees([8])
    assert groups[0][0] == "dcn"
    # A degree-4 tensor split stays entirely on ICI when batch took dcn.
    groups = m.axes_for_degrees([2, 4])
    assert groups[0] == ("dcn",)
    assert "dcn" not in groups[1]


def test_hybrid_machine_collapses_when_single_node(devices):
    m = dist.hybrid_machine(dcn_degree=1, devices=devices)
    assert "dcn" not in m.axis_names


def test_host_local_batch_single_process(devices):
    m = dist.hybrid_machine(dcn_degree=2, devices=devices)
    arr = np.arange(32, dtype=np.float32).reshape(16, 2)
    out = dist.host_local_batch(m, arr, degree=8)
    np.testing.assert_array_equal(np.asarray(out), arr)
    assert len(out.sharding.device_set) == 8


def test_model_trains_on_hybrid_mesh(devices):
    cfg = ff.FFConfig(batch_size=16, num_nodes=2, workers_per_node=4,
                      compute_dtype="float32")
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False)
    t = m.dense(inp, 16, activation="relu")
    t = m.dense(t, 4)
    m.softmax(t)
    m.compile(ff.SGDOptimizer(lr=0.5),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    assert m.machine.axis_names[0] == "dcn"
    m.init_layers()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 8), dtype=np.float32)
    y = np.argmax(x[:, :4], axis=1).astype(np.int32)[:, None]
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(20):
        dl.reset()
        for _ in range(dl.num_batches()):
            dl.next_batch(m)
            m.train_iteration()
    m.sync()
    acc = m.get_metrics().accuracy
    assert acc > 80.0, acc


def test_initialize_noop_single_process():
    dist.initialize()  # must not raise or hang on CPU single process
    assert dist.process_count() == 1
    assert dist.is_coordinator()
