"""bench.py degradation-ladder tests: the watchdog's last-line-always-
parseable invariant, stranded-phase attribution, ledger wiring, and
(slow) the forced-proxy acceptance run — ``JAX_PLATFORMS=cpu python
bench.py`` must exit 0 with a well-formed ``proxy: true`` result."""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, ".")

import bench  # noqa: E402

REPO = os.path.dirname(os.path.abspath(bench.__file__))


@pytest.fixture
def bench_state():
    """Snapshot/restore the bench module state the unit tests mutate."""
    saved = dict(bench._state)
    saved_extra = dict(bench._state["extra"])
    yield bench._state
    bench._state.update(saved)
    bench._state["extra"] = saved_extra


def _last_json(out):
    lines = [l for l in out.splitlines() if l.strip()]
    return json.loads(lines[-1])


def test_emit_primary_fields_land_top_level(capsys):
    line = bench._emit_primary(100.0, {"alexnet": {"batch": 256}},
                               mfu=0.25, proxy=True, backend="cpu",
                               stranded_phase="phase 'preflight'")
    out = capsys.readouterr().out
    assert _last_json(out) == line
    assert line["value"] == 100.0 and line["mfu"] == 0.25
    assert line["proxy"] is True and line["backend"] == "cpu"
    assert line["stranded_phase"] == "phase 'preflight'"
    assert line["extra"] == {"alexnet": {"batch": 256}}


def test_emit_primary_fresh_line_starts_at_column_zero(capsys):
    sys.stdout.write("half-written enriched li")  # no newline — mid-print
    bench._emit_primary(50.0, {}, fresh_line=True)
    out = capsys.readouterr().out
    assert _last_json(out)["value"] == 50.0  # tail line parses anyway


def test_read_stranded_phase_env_override(monkeypatch):
    monkeypatch.setenv("FF_BENCH_STRANDED", "phase 'alexnet' (120s stale)")
    assert bench._read_stranded_phase() == "phase 'alexnet' (120s stale)"
    monkeypatch.setenv("FF_BENCH_STRANDED", "")
    assert bench._read_stranded_phase() is None  # child with no parent info


def test_read_stranded_phase_from_heartbeat(tmp_path, monkeypatch):
    from flexflow_tpu.observability import health

    monkeypatch.delenv("FF_BENCH_STRANDED", raising=False)
    monkeypatch.setenv("FF_HEARTBEAT_PATH", str(tmp_path / "hb.json"))
    assert bench._read_stranded_phase() is None  # no previous run
    health.write_heartbeat("alexnet", step=7)
    desc = bench._read_stranded_phase()
    assert "alexnet" in desc and "step 7" in desc


def test_watchdog_fire_before_primary(tmp_path, monkeypatch, capsys,
                                      bench_state):
    ledger = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("FF_PERF_LEDGER", str(ledger))
    monkeypatch.setenv("FF_BENCH_EXTRA_PATH", str(tmp_path / "extra.json"))
    bench_state["primary_printed"] = False
    bench_state["stranded_phase"] = "phase 'preflight' (90s stale)"
    codes = []
    bench._watchdog_fire("phase 'preflight' budget", "preflight",
                         exit_fn=codes.append)
    assert codes == [1]  # no result -> rc 1
    rec = _last_json(capsys.readouterr().out)
    assert "watchdog" in rec["error"] and rec["value"] == 0.0
    assert rec["stranded_phase"] == "phase 'preflight' (90s stale)"
    entries = [json.loads(l) for l in ledger.read_text().splitlines()]
    assert entries[-1]["status"] == "killed"


def test_watchdog_fire_reflushes_primary_whole(tmp_path, monkeypatch,
                                               capsys, bench_state):
    monkeypatch.setenv("FF_BENCH_EXTRA_PATH", str(tmp_path / "extra.json"))
    primary = {"metric": "alexnet_train_samples_per_sec_per_chip",
               "value": 16902.0, "unit": "samples/s/chip", "mfu": 0.367}
    bench_state["primary_printed"] = True
    bench_state["primary_line"] = dict(primary)
    codes = []
    sys.stdout.write('{"metric": "alexnet_tr')  # main thread died mid-print
    bench._watchdog_fire("phase 'decode' budget", "decode",
                         exit_fn=codes.append)
    assert codes == [0]  # the primary made it out -> rc 0
    rec = _last_json(capsys.readouterr().out)
    assert rec["value"] == 16902.0 and rec["mfu"] == 0.367
    assert "decode" in rec["watchdog"]


def test_ledger_append_carries_provenance(tmp_path, monkeypatch):
    ledger = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("FF_PERF_LEDGER", str(ledger))
    line = {"metric": "alexnet_train_samples_per_sec_per_chip",
            "value": 41.5, "unit": "samples/s/chip", "mfu": 0.0,
            "proxy": True, "proxy_reason": "no chip answered",
            "stranded_phase": "phase 'alexnet'",
            "extra": {"proxy": {"model": "alexnet", "batch": 8,
                                "dtype": "float32", "backend": "cpu"}}}
    bench._ledger_append(line, status="ok", backend="cpu")
    e = json.loads(ledger.read_text().splitlines()[-1])
    assert e["proxy"] is True and e["backend"] == "cpu"
    assert e["batch"] == 8
    assert e["provenance"]["proxy_reason"] == "no chip answered"
    assert e["stranded_phase"] == "phase 'alexnet'"
    assert "commit" in e and "unix_time" in e


def test_last_good_summary_reads_ledger(tmp_path, monkeypatch):
    ledger = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("FF_PERF_LEDGER", str(ledger))
    pl = bench._ledger()
    pl.append_entry({"kind": "bench", "metric": "m", "value": 16902.0,
                     "unit": "samples/s/chip", "mfu": 0.367,
                     "status": "ok", "proxy": False}, path=str(ledger))
    lg = bench._last_good_summary()
    assert lg["value"] == 16902.0 and lg["mfu"] == 0.367
    assert "age_days" in lg


@pytest.mark.slow
def test_forced_proxy_bench_exits_zero(tmp_path):
    """The acceptance run: no chip (JAX_PLATFORMS=cpu), bench.py must
    degrade to a proxy metric and exit 0 — not die with rc=1/value 0."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               FF_BENCH_PROXY_BATCH="8", FF_BENCH_PROXY_STEPS="2",
               FF_PERF_LEDGER=str(tmp_path / "ledger.jsonl"),
               FF_BENCH_EXTRA_PATH=str(tmp_path / "extra.json"),
               FF_HEARTBEAT_PATH=str(tmp_path / "hb.json"))
    env.pop("FF_BENCH_FORCE_PROXY", None)  # the cpu pin alone must do it
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=600, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    rec = _last_json(r.stdout)
    assert rec["proxy"] is True and rec["backend"] == "cpu"
    assert rec["value"] > 0
    assert "cpu" in rec["proxy_reason"]
    entries = [json.loads(l)
               for l in open(tmp_path / "ledger.jsonl") if l.strip()]
    assert entries[-1]["proxy"] and entries[-1]["status"] == "ok"
    # the side file survived too
    extra = json.load(open(tmp_path / "extra.json"))
    assert extra["proxy"]["backend"] == "cpu"
