"""Autoscaler + zone-aware pool (flexflow_tpu/serving/autoscaler.py).

The policy half is unit-tested against a stub pool with a fake clock —
``Autoscaler._tick(now)`` is deterministic given the pool snapshot and
the timestamp, so the hysteresis/cooldown claims (scale up on queue
pressure only after the streak, no flapping inside the band, min/max
clamps, immediate backfill below min) never sleep.  The integration
half runs a real 2-zone pool on the tiny CPU transformer: round-robin
zone placement, graceful drain that stays bitwise-equal to
``generate()``, and the retired replica vanishing from ``healthz`` and
the Prometheus render (no dead ``ff_replica_up`` series forever).
"""

import threading
import time

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.models.transformer import build_transformer
from flexflow_tpu.observability.metrics import render_backend
from flexflow_tpu.serving import Autoscaler, ScaleConfig, ServeConfig
from flexflow_tpu.serving.pool import ReplicaPool
from flexflow_tpu.serving.queue import (TIMEOUT, InferenceRequest,
                                        RequestQueue)

V = 32
MAX_SEQ = 64


# ---------------------------------------------------------------------------
# loud knob parsing
# ---------------------------------------------------------------------------

def test_scale_env_garbage_is_loud(monkeypatch):
    monkeypatch.setenv("FF_SCALE_MAX", "banana")
    with pytest.raises(ValueError, match="FF_SCALE_MAX"):
        ScaleConfig.from_env()


def test_scale_env_min_zero_is_loud(monkeypatch):
    monkeypatch.setenv("FF_SCALE_MIN", "0")
    with pytest.raises(ValueError, match="FF_SCALE_MIN"):
        ScaleConfig.from_env()


def test_scale_min_above_max_is_loud():
    with pytest.raises(ValueError, match="FF_SCALE_MAX"):
        ScaleConfig(min_replicas=3, max_replicas=2)


def test_scale_streak_zero_is_loud(monkeypatch):
    monkeypatch.setenv("FF_SCALE_STREAK", "0")
    with pytest.raises(ValueError, match="FF_SCALE_STREAK"):
        ScaleConfig.from_env()


def test_scale_inverted_hysteresis_band_is_loud():
    with pytest.raises(ValueError, match="DOWN_QUEUE"):
        ScaleConfig(max_replicas=2, up_queue=1.0, down_queue=2.0)


def test_scale_env_roundtrip(monkeypatch):
    monkeypatch.setenv("FF_SCALE_MIN", "2")
    monkeypatch.setenv("FF_SCALE_MAX", "5")
    monkeypatch.setenv("FF_SCALE_UP_QUEUE", "3.5")
    cfg = ScaleConfig.from_env()
    assert (cfg.min_replicas, cfg.max_replicas, cfg.up_queue) == (2, 5, 3.5)
    assert cfg.enabled
    assert "replicas=[2,5]" in cfg.describe()


def test_scale_disabled_by_default():
    cfg = ScaleConfig.from_env()
    assert not cfg.enabled
    with pytest.raises(ValueError, match="disabled"):
        Autoscaler(_StubPool(), cfg).start()


def test_zones_env_parsing(monkeypatch):
    monkeypatch.setenv("FF_SERVE_ZONES", "zone-a, zone-b")
    assert ServeConfig.from_env().zones == ("zone-a", "zone-b")
    monkeypatch.setenv("FF_SERVE_ZONES", "a,,b")
    with pytest.raises(ValueError, match="FF_SERVE_ZONES"):
        ServeConfig.from_env()
    monkeypatch.setenv("FF_SERVE_ZONES", "a,b,a")
    with pytest.raises(ValueError, match="unique"):
        ServeConfig.from_env()


# ---------------------------------------------------------------------------
# policy: stub pool + fake clock, no threads, no sleeps
# ---------------------------------------------------------------------------

class _StubPool:
    def __init__(self, ready=2, queued=0):
        self.ready_replicas = ready
        self.num_replicas = ready
        self.num_queued = queued
        self._telemetry = None
        self.adds = 0
        self.drains = 0

    def add_replica(self, zone=None):
        self.adds += 1
        self.ready_replicas += 1
        self.num_replicas += 1
        return f"replica-{self.num_replicas}"

    def drain_replica(self, name=None):
        self.drains += 1
        self.ready_replicas -= 1
        self.num_replicas -= 1
        return f"replica-{self.num_replicas + 1}"


def _scaler(pool, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("streak", 2)
    kw.setdefault("up_cooldown_s", 2.0)
    kw.setdefault("down_cooldown_s", 15.0)
    return Autoscaler(pool, ScaleConfig(**kw))


def test_scale_up_on_queue_pressure_respects_streak():
    pool = _StubPool(ready=2, queued=20)     # 10/replica >> up_queue=4
    sc = _scaler(pool)
    sc._tick(0.0)
    assert pool.adds == 0, "one hot tick must not scale (streak=2)"
    sc._tick(1.0)
    assert pool.adds == 1 and pool.ready_replicas == 3
    ev = sc.timeline[-1]
    assert ev[1:] == (3, 3)


def test_scale_up_cooldown_blocks_consecutive_adds():
    pool = _StubPool(ready=1, queued=50)
    sc = _scaler(pool, up_cooldown_s=10.0)
    sc._tick(0.0)
    sc._tick(1.0)                            # streak met -> add
    assert pool.adds == 1
    for t in (2.0, 3.0, 4.0):                # still hot, inside cooldown
        sc._tick(t)
    assert pool.adds == 1, "cooldown must pace consecutive adds"
    sc._tick(12.0)
    sc._tick(13.0)                           # fresh streak past cooldown
    assert pool.adds == 2


def test_no_flap_inside_hysteresis_band():
    # queued/replica between down_queue and up_queue: neither direction
    pool = _StubPool(ready=2, queued=4)      # 2/replica, band is (0.5, 4)
    sc = _scaler(pool)
    for t in range(20):
        sc._tick(float(t))
    assert pool.adds == 0 and pool.drains == 0
    st = sc.stats()
    assert st["up_streak"] == 0 and st["down_streak"] == 0


def test_scale_down_quiet_respects_cooldown_and_min():
    pool = _StubPool(ready=3, queued=0)
    sc = _scaler(pool, min_replicas=2, down_cooldown_s=15.0)
    sc._last_down = 0.0                      # a recent (fake) drain
    sc._tick(1.0)
    sc._tick(2.0)                            # streak met, inside cooldown
    assert pool.drains == 0
    sc._tick(16.0)
    sc._tick(17.0)                           # past cooldown -> drain
    assert pool.drains == 1 and pool.ready_replicas == 2
    # at min now: quiet forever, never goes below
    for t in range(40, 80):
        sc._tick(float(t))
    assert pool.drains == 1
    assert sc.stats()["blocked_min"] > 0


def test_scale_up_clamped_at_max():
    pool = _StubPool(ready=4, queued=100)
    sc = _scaler(pool, max_replicas=4)
    for t in range(6):
        sc._tick(float(t))
    assert pool.adds == 0
    assert sc.stats()["blocked_max"] > 0


def test_backfill_below_min_is_immediate():
    # a zone outage just took the fleet below min: no streak required
    pool = _StubPool(ready=1, queued=0)
    sc = _scaler(pool, min_replicas=3, max_replicas=6, up_cooldown_s=0.0)
    sc._tick(0.0)
    sc._tick(0.1)
    assert pool.adds == 2 and pool.ready_replicas == 3
    sc._tick(0.2)                            # at min again: no more
    assert pool.adds == 2


def test_burn_rate_triggers_scale_up_without_queue():
    pool = _StubPool(ready=2, queued=0)
    sc = _scaler(pool, up_burn=2.0, down_cooldown_s=1e9)
    for w in ("5m", "1h"):
        sc._observe({"t": "gauge", "name": "slo_burn_rate", "v": 6.0,
                     "attrs": {"slo": "ttft", "window": w}})
    sc._observing = True
    sc._observe({"t": "gauge", "name": "slo_burn_rate", "v": 6.0,
                 "attrs": {"slo": "ttft", "window": "5m"}})
    assert sc.burn_rate() == 6.0
    sc._tick(0.0)
    sc._tick(1.0)
    assert pool.adds == 1, "burn above FF_SCALE_UP_BURN must scale up"


# ---------------------------------------------------------------------------
# queue sweeper: expiry without anyone popping (drain hardening)
# ---------------------------------------------------------------------------

def test_queue_sweeper_expires_without_pops():
    q = RequestQueue()
    q.start_sweeper(interval_s=0.01)
    q.start_sweeper(interval_s=0.01)         # idempotent
    try:
        r = InferenceRequest([1, 2, 3], 4, timeout_s=0.05)
        q.put(r)
        deadline = time.perf_counter() + 5.0
        while not r.done() and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert r.done() and r.status == TIMEOUT, (r.status, r.error)
        assert len(q) == 0
    finally:
        q.stop_sweeper()
    assert q._sweeper is None or not q._sweeper.is_alive()


# ---------------------------------------------------------------------------
# integration: real 2-zone pool on the tiny CPU transformer
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    cfg = ff.FFConfig(batch_size=4)
    m = ff.FFModel(cfg)
    build_transformer(m, 4, seq_length=MAX_SEQ, num_layers=1,
                      embed_dim=16, num_heads=2, vocab_size=V)
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=3)
    return m


def _cfg(**kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq", MAX_SEQ)
    kw.setdefault("replica_timeout_s", 120.0)
    kw.setdefault("restart_backoff_s", 0.05)
    kw.setdefault("restart_cap_s", 0.2)
    return ServeConfig(**kw)


def test_zone_round_robin_placement(model):
    with ReplicaPool(model, config=_cfg(
            replicas=4, zones=("za", "zb"))) as pool:
        hz = pool.healthz()
        assert hz["zones"]["za"]["total"] == 2
        assert hz["zones"]["zb"]["total"] == 2
        by_zone = {}
        for r in hz["replicas"]:
            by_zone.setdefault(r["zone"], []).append(r["name"])
        assert sorted(by_zone) == ["za", "zb"]
        # add_replica backfills the least-populated zone
        name = pool.add_replica()
        assert name is not None
        zones = [r["zone"] for r in pool.healthz()["replicas"]]
        assert sorted((zones.count("za"), zones.count("zb"))) == [2, 3]


def test_graceful_drain_bitwise_and_series_retired(model):
    prompts = [np.array([5, 6, 7, 8], np.int32),
               np.array([9, 10, 11], np.int32),
               np.array([3, 1, 4, 1, 5], np.int32),
               np.array([2, 7, 1, 8, 2, 8], np.int32)]
    want = [model.generate(p[None], 6)[0] for p in prompts]
    with ReplicaPool(model, config=_cfg(replicas=2)) as pool:
        handles = [pool.submit(p, 6) for p in prompts]
        victim = pool.drain_replica(timeout=120.0)
        assert victim is not None
        outs = [h.result(120) for h in handles]
        for i, (got, w) in enumerate(zip(outs, want)):
            assert np.array_equal(got, w), f"drain broke request {i}"
        hz = pool.healthz()
        # satellite: the retired replica is GONE, not a zombie series
        assert victim not in [r["name"] for r in hz["replicas"]], hz
        assert len(hz["replicas"]) == 1
        rendered = render_backend(pool)
        assert f'replica="{victim}"' not in rendered
        assert "ff_replica_up" in rendered
        st = pool.stats()
        assert st["replicas_retired"] == 1
        assert st["completed"] + st["failovers"] >= len(prompts)


def test_autoscaler_live_backfill_below_min(model):
    # drop a replica under the scaler's feet: the next tick backfills
    with ReplicaPool(model, config=_cfg(replicas=2)) as pool:
        sc = Autoscaler(pool, ScaleConfig(
            min_replicas=2, max_replicas=3, interval_s=0.02,
            streak=2, up_cooldown_s=0.05, down_cooldown_s=1e9))
        with sc:
            pool.drain_replica()
            deadline = time.perf_counter() + 30.0
            while time.perf_counter() < deadline:
                if pool.ready_replicas >= 2:
                    break
                time.sleep(0.02)
            assert pool.ready_replicas >= 2, pool.healthz()
        assert sc.stats()["scale_ups"] >= 1
        assert pool.stats()["replicas_added"] >= 1


def test_add_replica_refused_while_stopped(model):
    pool = ReplicaPool(model, config=_cfg(replicas=1))
    assert pool.add_replica() is None       # not started yet
    with pool:
        assert pool.add_replica() is not None
    assert pool.add_replica() is None       # stopped
