"""Block-paged KV cache (flexflow_tpu/serving/kvpool.py + paged engine).

The load-bearing claims: paging the slot kv pool into refcounted
fixed-size blocks is TRANSPARENT (every greedy output stays bitwise the
tokens one-shot ``FFModel.generate()`` produces), admission moves only
the prompt's own blocks instead of a whole max_seq slice, a shared
prompt prefix is prefilled ONCE (later requests gather the cached
chain and compute only their suffix — still bitwise-identical),
copy-on-write keeps divergent continuations from corrupting each
other, and block exhaustion is an admission shed (HTTP 503 +
``Retry-After``), never a crash or a leaked block.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.models.transformer import build_transformer
from flexflow_tpu.serving import ServeConfig, ServeOverload
from flexflow_tpu.serving.engine import InferenceEngine
from flexflow_tpu.serving.kvpool import (BlockExhausted, KVBlockPool,
                                         blocks_for)
from flexflow_tpu.serving.pool import ReplicaPool
from flexflow_tpu.testing.chaos import ChaosMonkey

V = 32          # vocab
MAX_SEQ = 64    # default kv_block=16 -> 4 blocks per worst-case seq


def _make_model(seed=3):
    cfg = ff.FFConfig(batch_size=4)
    m = ff.FFModel(cfg)
    build_transformer(m, 4, seq_length=MAX_SEQ, num_layers=1,
                      embed_dim=16, num_heads=2, vocab_size=V)
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=seed)
    return m


@pytest.fixture(scope="module")
def model():
    return _make_model()


def _prompts(n, seed=0, lo=3, hi=28):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, V, size=int(rng.integers(lo, hi + 1)))
            .astype(np.int32) for _ in range(n)]


# ---------------------------------------------------------------------------
# pool unit: allocator / prefix index / reservation accounting
# ---------------------------------------------------------------------------

def test_kvpool_reserve_release_accounting():
    pool = KVBlockPool(9, 16, bytes_per_block=1024)  # 8 usable + sink
    toks = list(range(40))                           # 3 blocks
    res = pool.reserve(toks, max_new=10)             # worst case 4
    assert len(res.table()) == blocks_for(40, 16) == 3
    assert res.promised == 1                         # ceil(50/16)=4 - 3
    pool.register_prefix(toks, res)
    pool.extend(res, pos=48)                         # crosses into block 4
    assert len(res.table()) == 4
    pool.release(res)
    assert pool.slot_refs() == 0                     # index refs excluded
    st = pool.stats()
    assert st["blocks_promised"] == 0
    # the index still pins the full prompt blocks for reuse
    assert st["index_entries"] >= 1 and st["blocks_used"] >= 2

    # a second identical prompt hits the exact-prompt entry
    res2 = pool.reserve(toks, max_new=10)
    assert res2.hit_tokens > 0 and pool.stats()["prefix_hits"] == 1
    pool.end_gather(res2)
    pool.release(res2)
    assert pool.slot_refs() == 0


def test_kvpool_exhaustion_sheds_not_crashes():
    pool = KVBlockPool(3, 16, bytes_per_block=64)    # 2 usable blocks
    with pytest.raises(BlockExhausted):
        pool.check_room(40, 10)                      # needs 4 > 2
    ok = pool.reserve(list(range(16)), max_new=8)    # needs 2: fits
    with pytest.raises(BlockExhausted) as ei:
        pool.reserve(list(range(100, 116)), max_new=8)
    assert ei.value.retry_after_s > 0
    assert pool.stats()["sheds"] >= 1
    pool.release(ok)
    assert pool.slot_refs() == 0


# ---------------------------------------------------------------------------
# bitwise greedy parity on mixed-length batches
# ---------------------------------------------------------------------------

def test_paged_greedy_parity_mixed_lengths(model):
    prompts = _prompts(8, seed=1)
    news = [6, 16, 4, 12, 9, 15, 8, 10]
    eng = InferenceEngine(model, max_batch=4, max_seq=MAX_SEQ,
                          max_new_tokens=32)
    assert eng._paged, "paged mode should self-enable on this geometry"
    with eng:
        handles = [eng.submit(p, n) for p, n in zip(prompts, news)]
        outs = [h.result(120) for h in handles]
    for i, (p, n, got) in enumerate(zip(prompts, news, outs)):
        assert np.array_equal(got, model.generate(p[None], n)[0]), i
    st = eng.stats()
    assert st["paged"] and st["kv"]["blocks_peak"] > 0
    assert st["kv"]["blocks_promised"] == 0 and eng._kvpool.slot_refs() == 0


# ---------------------------------------------------------------------------
# admission moves only the prompt's blocks (satellite: no whole-slice copy)
# ---------------------------------------------------------------------------

def test_admission_transfers_only_prompt_blocks(model):
    # 8-token prompt, block 16: the suffix bucket (8) spans
    # ceil(8/16)+1 = 2 scatter blocks (the +1 absorbs an unaligned
    # start).  The dense engine inserted a whole max_seq slice — 4
    # blocks' worth — per admission regardless of prompt length; the
    # transferred-bytes ledger must show the difference.
    p = np.arange(8, dtype=np.int32) % V
    eng = InferenceEngine(model, max_batch=2, max_seq=MAX_SEQ,
                          max_new_tokens=8)
    with eng:
        out = eng.submit(p, 6).result(120)
    assert np.array_equal(out, model.generate(p[None], 6)[0])
    st = eng.stats()["kv"]
    bpb = eng._kvpool.bytes_per_block
    dense_slice_bytes = (MAX_SEQ // st["block_size"]) * bpb
    assert st["transferred_blocks"] == blocks_for(8, 16) + 1 == 2
    assert st["transferred_bytes"] == 2 * bpb < dense_slice_bytes


# ---------------------------------------------------------------------------
# prefix cache: warm admission is bitwise the cold one, suffix-only prefill
# ---------------------------------------------------------------------------

def test_prefix_hit_bitwise_identical_to_cold_prefill(model):
    p = _prompts(1, seed=7, lo=24, hi=24)[0]        # 1 full + 1 partial
    eng = InferenceEngine(model, max_batch=2, max_seq=MAX_SEQ,
                          max_new_tokens=16)
    with eng:
        cold = eng.submit(p, 10).result(120)        # registers the prefix
        warm = eng.submit(p, 10).result(120)        # gathers it back
        st = eng.stats()["kv"]
    want = model.generate(p[None], 10)[0]
    assert np.array_equal(cold, want)
    assert np.array_equal(warm, cold)
    assert st["prefix_hits"] >= 1 and st["prefix_hit_rate"] > 0
    assert st["prefill_tokens_saved"] > 0
    assert st["gathered_blocks"] >= 1


def test_cow_divergence_after_shared_prefix(model):
    # base prompt ends mid-block (24 = 16 + 8): continuations that hit
    # its cached chain share the full block read-only but must COW the
    # partial tail before writing their own suffix — and the donor's
    # own generated tokens must never bleed into a sharer's output.
    rng = np.random.default_rng(11)
    base = rng.integers(0, V, size=24).astype(np.int32)
    ext_a = np.concatenate([base, np.array([1, 2], np.int32)])
    ext_b = np.concatenate([base, np.array([3], np.int32)])
    eng = InferenceEngine(model, max_batch=2, max_seq=MAX_SEQ,
                          max_new_tokens=16)
    with eng:
        outs = {}
        outs["base"] = eng.submit(base, 12).result(120)
        outs["a"] = eng.submit(ext_a, 12).result(120)
        outs["b"] = eng.submit(ext_b, 12).result(120)
        outs["base2"] = eng.submit(base, 12).result(120)
        st = eng.stats()["kv"]
    for key, prompt in (("base", base), ("a", ext_a), ("b", ext_b),
                        ("base2", base)):
        want = model.generate(prompt[None], 12)[0]
        assert np.array_equal(outs[key], want), key
    assert np.array_equal(outs["base2"], outs["base"])
    assert st["prefix_hits"] >= 3
    assert st["cow_copies"] >= 1, "partial-tail share never COWed"
    assert eng._kvpool.slot_refs() == 0


# ---------------------------------------------------------------------------
# exhaustion under load: HTTP 503 + Retry-After, zero leaked blocks
# ---------------------------------------------------------------------------

def test_block_exhaustion_503_retry_after_no_leak(model):
    from flexflow_tpu.serving.api import ServingAPI

    # 2 usable blocks: one 20-token prompt + headroom promises both;
    # a concurrent admission must shed at submit, not crash mid-decode
    eng = InferenceEngine(model, max_batch=2, max_seq=MAX_SEQ,
                          max_new_tokens=8, kv_blocks=2)
    p_big = np.arange(20, dtype=np.int32) % V       # ceil(28/16) = 2
    with eng, ServingAPI(eng, port=0) as api:
        h = eng.submit(p_big, 8)
        body = json.dumps({"prompt": [int(t) for t in p_big],
                           "max_new_tokens": 8}).encode()
        req = urllib.request.Request(
            f"{api.url}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        err = ei.value
        assert err.code == 503
        assert int(err.headers["Retry-After"]) >= 1
        detail = json.loads(err.read()).get("error", "")
        assert detail.startswith("kv blocks exhausted"), detail
        # the in-flight request is untouched by the shed
        assert np.array_equal(h.result(120),
                              model.generate(p_big[None], 8)[0])
        # drained: blocks all returned, and the SAME prompt now admits
        assert eng._kvpool.slot_refs() == 0
        out2 = eng.submit(p_big, 8).result(120)
        assert np.array_equal(out2, model.generate(p_big[None], 8)[0])
    st = eng.stats()["kv"]
    assert st["sheds"] >= 1 and st["blocks_promised"] == 0
    assert eng._kvpool.slot_refs() == 0


# ---------------------------------------------------------------------------
# chaos: a replica killed mid-flight leaves no dangling block refs
# ---------------------------------------------------------------------------

def test_refcounts_zero_after_chaos_replica_kill(model, monkeypatch):
    # 3rd pool-wide admission raises ChaosReplicaKill inside whichever
    # replica pops it; the dying loop must release every reservation it
    # holds (in-flight slots AND the mid-admit request) before the pool
    # fails its work over.
    monkeypatch.setattr(model, "_chaos", ChaosMonkey("serve:3=replica_kill"))
    prompts = _prompts(8, seed=2)
    cfg = ServeConfig(max_batch=2, max_seq=MAX_SEQ, replicas=2,
                      replica_timeout_s=120.0,
                      restart_backoff_s=0.05, restart_cap_s=0.2)
    engines = []
    with ReplicaPool(model, config=cfg) as pool:
        engines.extend(r.engine for r in pool._replicas)
        handles = [pool.submit(p, 8) for p in prompts]
        outs = [h.result(120) for h in handles]
        st = pool.stats()
        # restarted incarnations too (fresh engine objects)
        engines.extend(r.engine for r in pool._replicas)
    for i, (p, got) in enumerate(zip(prompts, outs)):
        assert np.array_equal(got, model.generate(p[None], 8)[0]), i
    assert st["replica_downs"] >= 1 and st["completed"] == 8
    seen = {id(e): e for e in engines if e is not None}
    assert len(seen) >= 3, "expected at least one restarted incarnation"
    for e in seen.values():
        if e._paged:
            assert e._kvpool.slot_refs() == 0, e.uid
            assert e._kvpool.stats()["blocks_promised"] == 0, e.uid


# ---------------------------------------------------------------------------
# capacity headline: equal block budget holds 2x the dense slot count
# ---------------------------------------------------------------------------

def test_paged_outadmits_dense_at_equal_budget(model):
    # dense equivalent of max_batch=2 is 8 blocks (2 x 64/16).  With
    # short prompts the paged engine keeps 4+ sequences' blocks live on
    # that same budget — the dense pool by construction never exceeds 2.
    eng = InferenceEngine(model, max_batch=4, max_seq=MAX_SEQ,
                          max_new_tokens=8, kv_blocks=8)
    prompts = _prompts(6, seed=5, lo=4, hi=10)
    with eng:
        handles = [eng.submit(p, 8) for p in prompts]
        outs = [h.result(120) for h in handles]
    for i, (p, got) in enumerate(zip(prompts, outs)):
        assert np.array_equal(got, model.generate(p[None], 8)[0]), i
    st = eng.stats()
    assert st["max_active"] >= 4 > 2   # 2 = dense slots on this budget
    assert eng._kvpool.slot_refs() == 0
