"""End-to-end request tracing (flexflow_tpu/observability/reqtrace.py).

The load-bearing claims: the sampling decision is deterministic in the
trace id (made once at admission, re-derivable anywhere); a failover
leaves BOTH attempts as sibling child spans under one trace so the race
is visible in the timeline; and with telemetry off the tracing plane
performs zero event-log calls and mints zero contexts.
"""

import collections
import json

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.models.transformer import build_transformer
from flexflow_tpu.observability import events, reqtrace
from flexflow_tpu.serving.config import ServeConfig
from flexflow_tpu.serving.engine import InferenceEngine
from flexflow_tpu.serving.pool import ReplicaPool
from flexflow_tpu.testing.chaos import ChaosMonkey

V = 32
MAX_SEQ = 64


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in ("FF_TELEMETRY", "FF_TELEMETRY_FILE", "FF_TRACE_SAMPLE",
                "FF_TRACE_CHUNK"):
        monkeypatch.delenv(var, raising=False)
    events.reset_active()
    yield
    events.reset_active()


def _make_model(seed=3):
    cfg = ff.FFConfig(batch_size=4)
    m = ff.FFModel(cfg)
    build_transformer(m, 4, seq_length=MAX_SEQ, num_layers=1,
                      embed_dim=16, num_heads=2, vocab_size=V)
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=seed)
    return m


@pytest.fixture(scope="module")
def model():
    return _make_model()


def _prompts(n, seed=0, lo=3, hi=11):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, V, size=int(rng.integers(lo, hi + 1)))
            .astype(np.int32) for _ in range(n)]


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# unit: ids, sampling, context shape
# ---------------------------------------------------------------------------

def test_id_shapes():
    assert len(reqtrace.new_trace_id()) == 32
    assert len(reqtrace.new_span_id()) == 16
    int(reqtrace.new_trace_id(), 16)  # hex
    # run-level id is derived, not random: same run_id -> same trace
    assert reqtrace.run_trace_id("r1") == reqtrace.run_trace_id("r1")
    assert reqtrace.run_trace_id("r1") != reqtrace.run_trace_id("r2")


def test_sampling_deterministic_and_proportional():
    tid = reqtrace.new_trace_id()
    assert not reqtrace.decide(tid, 0.0)
    assert reqtrace.decide(tid, 1.0)
    # same id + rate always decides the same way
    for rate in (0.1, 0.5, 0.9):
        assert reqtrace.decide(tid, rate) == reqtrace.decide(tid, rate)
    # over many ids the hit rate tracks the probability (hash quality)
    ids = [reqtrace.new_trace_id() for _ in range(2000)]
    hits = sum(reqtrace.decide(t, 0.25) for t in ids)
    assert 0.18 < hits / len(ids) < 0.32
    # monotone: an id sampled at rate r stays sampled at every r' > r
    for t in ids[:100]:
        if reqtrace.decide(t, 0.25):
            assert reqtrace.decide(t, 0.5)


def test_sample_rate_env_loud(monkeypatch):
    assert reqtrace.sample_rate_from_env() == 0.0
    monkeypatch.setenv("FF_TRACE_SAMPLE", "0.25")
    assert reqtrace.sample_rate_from_env() == 0.25
    monkeypatch.setenv("FF_TRACE_SAMPLE", "banana")
    with pytest.raises(ValueError, match="FF_TRACE_SAMPLE"):
        reqtrace.sample_rate_from_env()
    monkeypatch.setenv("FF_TRACE_SAMPLE", "1.5")
    with pytest.raises(ValueError, match="outside"):
        reqtrace.sample_rate_from_env()
    monkeypatch.setenv("FF_TRACE_CHUNK", "-1")
    with pytest.raises(ValueError, match="FF_TRACE_CHUNK"):
        reqtrace.chunk_tokens_from_env()


def test_context_child_and_tags():
    root = reqtrace.TraceContext("ab" * 16, "cd" * 8, None, True)
    att = root.child()
    assert att.trace_id == root.trace_id
    assert att.parent_span_id == root.span_id
    assert att.span_id != root.span_id and att.sampled
    assert reqtrace.tag(None) == {}
    # unsampled: the 16-byte id only, no span linkage
    cold = reqtrace.TraceContext("ef" * 16, "01" * 8, None, False)
    assert reqtrace.tag(cold) == {"trace_id": "ef" * 16}
    assert reqtrace.tag(att) == {"trace_id": root.trace_id,
                                 "parent_span_id": att.span_id}
    assert set(root.ids()) == {"trace_id", "span_id"}
    assert set(att.ids()) == {"trace_id", "span_id", "parent_span_id"}


def test_begin_none_log_is_free():
    assert reqtrace.begin(None) is None


# ---------------------------------------------------------------------------
# engine: sampled request records join under one trace
# ---------------------------------------------------------------------------

def test_engine_records_share_trace(model, tmp_path, monkeypatch):
    monkeypatch.setenv("FF_TRACE_SAMPLE", "1")
    monkeypatch.setenv("FF_TRACE_CHUNK", "4")
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    with InferenceEngine(model, max_batch=2, max_seq=MAX_SEQ,
                         max_new_tokens=16, telemetry=log) as eng:
        req = eng.submit(_prompts(1, seed=7)[0], 12)
        req.result(120)
        assert req.trace is not None and req.trace.sampled
    log.close()

    recs = _read_jsonl(log.path)
    mine = [r for r in recs
            if (r.get("attrs") or {}).get("trace_id")
            == req.trace.trace_id]
    names = collections.Counter(r["name"] for r in mine)
    assert names["serve_queue_wait"] == 1
    assert names["serve_prefill"] == 1
    assert names["serve_decode"] == 1
    assert names["serve_request_done"] == 1
    # 12 tokens / chunk 4 -> 3 chunk spans, contiguous token ranges
    chunks = sorted((r for r in mine if r["name"] == "serve_decode_chunk"),
                    key=lambda r: r["attrs"]["token_from"])
    assert len(chunks) == 3
    for a, b in zip(chunks, chunks[1:]):
        assert a["attrs"]["token_to"] == b["attrs"]["token_from"]
    # sub-records parent to the request's own span
    for r in mine:
        assert r["attrs"]["parent_span_id"] == req.trace.span_id


def test_unsampled_request_id_only(model, tmp_path, monkeypatch):
    monkeypatch.setenv("FF_TRACE_SAMPLE", "0")
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    with InferenceEngine(model, max_batch=2, max_seq=MAX_SEQ,
                         max_new_tokens=16, telemetry=log) as eng:
        req = eng.submit(_prompts(1, seed=8)[0], 6)
        req.result(120)
        assert req.trace is not None and not req.trace.sampled
    log.close()
    recs = _read_jsonl(log.path)
    mine = [r for r in recs
            if (r.get("attrs") or {}).get("trace_id")
            == req.trace.trace_id]
    # records still join on the id, but carry no span linkage and no
    # chunk spans / KV events rode along
    assert {r["name"] for r in mine} <= {
        "serve_queue_wait", "serve_prefill", "serve_decode",
        "serve_request_done"}
    assert all("parent_span_id" not in r["attrs"] for r in mine)


# ---------------------------------------------------------------------------
# pool: failover leaves sibling attempt spans under one trace
# ---------------------------------------------------------------------------

def test_failover_attempts_are_siblings(model, tmp_path, monkeypatch):
    monkeypatch.setenv("FF_TRACE_SAMPLE", "1")
    monkeypatch.setattr(model, "_chaos",
                        ChaosMonkey("serve:3=replica_kill"))
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    cfg = ServeConfig(max_batch=2, max_seq=MAX_SEQ, replicas=3,
                      replica_timeout_s=120.0, restart_backoff_s=0.05,
                      restart_cap_s=0.2)
    prompts = _prompts(8, seed=2)
    with ReplicaPool(model, config=cfg, telemetry=log) as pool:
        handles = [pool.submit(p, 8) for p in prompts]
        outs = [h.result(120) for h in handles]
        st = pool.stats()
    log.close()
    for p, got in zip(prompts, outs):
        assert np.array_equal(got, model.generate(p[None], 8)[0])
    assert st["failovers"] >= 1, "the kill never caught a request"

    recs = _read_jsonl(log.path)
    fo = [r for r in recs if r.get("name") == "request_failover"]
    assert fo and all(r["attrs"].get("trace_id") for r in fo)
    tid = fo[0]["attrs"]["trace_id"]
    mine = [r for r in recs
            if (r.get("attrs") or {}).get("trace_id") == tid]
    roots = [r for r in mine if r["name"] == "serve_request"]
    atts = [r for r in mine if r["name"] == "serve_attempt"]
    assert len(roots) == 1
    assert len(atts) >= 2, "failover must leave both attempt spans"
    root_span = roots[0]["attrs"]["span_id"]
    # every attempt is a CHILD of the client root -> siblings
    for a in atts:
        assert a["attrs"]["parent_span_id"] == root_span
        assert "#a" in a["attrs"]["request_id"]
    # attempt incarnations differ (the race is visible)
    assert len({a["attrs"]["incarnation"] for a in atts}) >= 2
    # the root span covers its attempts (same submit clock)
    t0 = roots[0]["ts"]
    t1 = t0 + roots[0]["dur"]
    for a in atts:
        assert a["ts"] >= t0 - 1e-6
        assert a["ts"] + a["dur"] <= t1 + 0.05


# ---------------------------------------------------------------------------
# zero-cost when disabled
# ---------------------------------------------------------------------------

def test_disabled_zero_log_calls(model, monkeypatch):
    calls = []
    monkeypatch.setattr(
        events.EventLog, "_write",
        lambda self, rec: calls.append(rec))
    with InferenceEngine(model, max_batch=2, max_seq=MAX_SEQ,
                         max_new_tokens=8) as eng:   # telemetry=None
        req = eng.submit(_prompts(1, seed=9)[0], 4)
        req.result(120)
    assert req.trace is None          # no context was ever minted
    assert calls == []                # and no record was ever written
