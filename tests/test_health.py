"""Health monitor tests: heartbeat file protocol, straggler / data-
starvation detection on synthetic step streams, injected-NaN detection
through the real (CPU) train step within one sampling window, the
zero-calls-when-disabled invariant, and a byte-exact golden check for
tools/health_report.py."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, ".")

import flexflow_tpu as ff
from flexflow_tpu.observability import events, health
from flexflow_tpu.tools import health_report

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "health_report.md")


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    """Fresh singleton + clean health env per test."""
    for var in ("FF_TELEMETRY", "FF_TELEMETRY_FILE", "FF_HEALTH",
                "FF_HEALTH_SAMPLE_EVERY", "FF_HEALTH_STRAGGLER_K",
                "FF_HEALTH_DATA_WAIT_RATIO", "FF_HEARTBEAT_PATH"):
        monkeypatch.delenv(var, raising=False)
    events.reset_active()
    yield
    events.reset_active()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _health_events(recs):
    return [r for r in recs if r["t"] == "event" and r["name"] == "health"]


# ---------------------------------------------------------------------------
# heartbeat file
# ---------------------------------------------------------------------------

def test_heartbeat_roundtrip(tmp_path, monkeypatch):
    hb = tmp_path / "hb.json"
    monkeypatch.setenv("FF_HEARTBEAT_PATH", str(hb))
    health.write_heartbeat("compile")
    health.write_heartbeat("step", step=7)
    rec = health.read_heartbeat()
    assert rec["phase"] == "step" and rec["step"] == 7
    desc = health.describe_heartbeat(rec, now=rec["unix_time"] + 12.0)
    assert "phase 'step'" in desc and "step 7" in desc and "12s stale" in desc


def test_heartbeat_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    health.write_heartbeat("anything", step=1)
    assert health.read_heartbeat() is None
    assert os.listdir(tmp_path) == []


def test_heartbeat_corrupt_file_tolerated(tmp_path, monkeypatch):
    hb = tmp_path / "hb.json"
    hb.write_text('{"phase": "ste')  # kill raced the atomic replace
    monkeypatch.setenv("FF_HEARTBEAT_PATH", str(hb))
    assert health.read_heartbeat() is None
    assert health.describe_heartbeat(None) is None


# ---------------------------------------------------------------------------
# straggler / starvation on synthetic step streams (no jax)
# ---------------------------------------------------------------------------

def test_straggler_attributed_to_overlapping_span(tmp_path):
    log = events.EventLog(str(tmp_path / "t.jsonl"), clock=lambda: 0.0)
    hm = health.HealthMonitor(None, log, sample_every=0,
                              straggler_k=3.0, min_window=4)
    log.add_observer(hm.observe)
    t = 0.0
    for i in range(6):  # steady 10 ms steps build the rolling median
        hm.on_step(i, t, 0.010, first=(i == 0))
        t += 0.012
    # a slow host gather lands in the gap before the straggler step
    log.span_at("data_wait", t + 0.001, 0.08, batch_size=4)
    hm.on_step(6, t + 0.002, 0.1, first=False)
    log.close()

    evs = _health_events(_read_jsonl(log.path))
    assert len(evs) == 1
    a = evs[0]["attrs"]
    assert a["kind"] == "straggler" and a["step"] == 6
    assert a["attribution"] == "data_wait"
    assert a["ratio"] >= 3.0


def test_straggler_without_overlap_is_unknown(tmp_path):
    log = events.EventLog(str(tmp_path / "t.jsonl"), clock=lambda: 0.0)
    hm = health.HealthMonitor(None, log, sample_every=0,
                              straggler_k=3.0, min_window=4)
    t = 0.0
    for i in range(6):
        hm.on_step(i, t, 0.010, first=(i == 0))
        t += 0.012
    hm.on_step(6, t, 0.1, first=False)
    log.close()
    (ev,) = _health_events(_read_jsonl(log.path))
    assert ev["attrs"]["attribution"] == "unknown"


def test_data_starvation_detected_per_window(tmp_path):
    log = events.EventLog(str(tmp_path / "t.jsonl"), clock=lambda: 0.0)
    hm = health.HealthMonitor(None, log, sample_every=4, wait_ratio=0.3,
                              min_window=99)
    log.add_observer(hm.observe)
    t = 0.0
    for i in range(5):  # waits comparable to step time -> starved
        log.span_at("data_wait", t, 0.008, batch_size=4)
        hm.on_step(i, t + 0.008, 0.010, first=(i == 0))
        t += 0.02
    log.close()
    evs = _health_events(_read_jsonl(log.path))
    assert [e["attrs"]["kind"] for e in evs] == ["data_starvation"]
    assert evs[0]["attrs"]["ratio"] > 0.3


def test_event_cap_per_kind(tmp_path):
    log = events.EventLog(str(tmp_path / "t.jsonl"), clock=lambda: 0.0)
    hm = health.HealthMonitor(None, log, sample_every=0)
    for i in range(health.MAX_EVENTS_PER_KIND + 50):
        hm._emit("nonfinite_loss", step=i)
    log.close()
    evs = _health_events(_read_jsonl(log.path))
    assert len(evs) == health.MAX_EVENTS_PER_KIND
    assert evs[-1]["attrs"].get("suppressing_further") is True


# ---------------------------------------------------------------------------
# real training loop (CPU mesh)
# ---------------------------------------------------------------------------

def _tiny_model(batch=16):
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="float32")
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 8), nchw=False)
    t = m.dense(inp, 16, activation=ff.ActiMode.RELU)
    m.softmax(m.dense(t, 4))
    return m, inp


def _train_steps(m, inp, steps):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((m.config.batch_size * steps, 8), np.float32)
    y = rng.integers(0, 4, (m.config.batch_size * steps, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()


def test_injected_nan_flagged_within_one_window(devices, tmp_path,
                                                monkeypatch):
    trace = tmp_path / "run.jsonl"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    monkeypatch.setenv("FF_HEALTH", "1")
    monkeypatch.setenv("FF_HEALTH_SAMPLE_EVERY", "2")
    m, inp = _tiny_model()
    m.compile(ff.SGDOptimizer(lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    assert m._health is not None
    assert set(health.HEALTH_METRIC_KEYS) <= set(m._metric_keys())
    m.init_layers()
    # poison one weight tensor: loss and grads go NaN from step 0
    import jax

    leaves, treedef = jax.tree.flatten(m._params)
    leaves[0] = leaves[0] * np.nan
    m._params = jax.tree.unflatten(treedef, leaves)
    _train_steps(m, inp, 2)  # exactly one sampling window, no get_metrics
    events.reset_active()

    recs = _read_jsonl(str(trace))
    kinds = {e["attrs"]["kind"] for e in _health_events(recs)}
    assert "nonfinite_loss" in kinds
    assert "nonfinite_grad" in kinds
    # the compile-time simulator prediction rode along
    assert any(r["t"] == "event" and r["name"] == "sim_prediction"
               for r in recs)
    # and health_report surfaces the finding
    report = health_report.render_report(recs)
    assert "nonfinite_loss" in report and "## Health findings" in report


def test_healthy_run_emits_no_findings(devices, tmp_path, monkeypatch):
    trace = tmp_path / "run.jsonl"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    monkeypatch.setenv("FF_HEALTH", "1")
    monkeypatch.setenv("FF_HEALTH_SAMPLE_EVERY", "2")
    m, inp = _tiny_model()
    m.compile(ff.SGDOptimizer(lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers()
    _train_steps(m, inp, 4)
    m.get_metrics()
    events.reset_active()
    recs = _read_jsonl(str(trace))
    assert not [e for e in _health_events(recs)
                if e["attrs"]["kind"].startswith("nonfinite")]
    # grad-norm gauge rode the drain
    assert any(r["t"] == "gauge" and r["name"] == "grad_global_norm"
               for r in recs)


def test_disabled_telemetry_zero_health_calls(devices, tmp_path,
                                              monkeypatch):
    """FF_HEALTH=1 alone (telemetry off): no monitor, no event-log or
    health calls anywhere on the hot path — any would raise."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("FF_HEALTH", "1")

    def _boom(*a, **k):
        raise AssertionError("health/event-log call while disabled")

    monkeypatch.setattr(events.EventLog, "_write", _boom)
    monkeypatch.setattr(health.HealthMonitor, "on_step", _boom)
    monkeypatch.setattr(health.HealthMonitor, "on_drain", _boom)
    monkeypatch.setattr(health.HealthMonitor, "observe", _boom)
    m, inp = _tiny_model()
    m.compile(ff.SGDOptimizer(lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    assert m._telemetry is None and m._health is None
    # metric vector stays at its base 9 entries: the isfinite reduction
    # is not even traced into the step
    assert len(m._metric_keys()) == 9
    m.init_layers()
    _train_steps(m, inp, 2)
    m.get_metrics()
    assert not os.path.exists("ff_trace.jsonl")


# ---------------------------------------------------------------------------
# health_report golden
# ---------------------------------------------------------------------------

def synthetic_records():
    """Deterministic trace exercising every health_report section."""
    recs = [{"t": "meta", "version": 1, "run_id": "health-golden",
             "pid": 4242, "unix_time": 1700000000.0}]
    recs.append({"t": "span", "name": "compile", "id": 1, "parent": None,
                 "ts": 0.1, "dur": 1.25, "attrs": {"num_ops": 6}})
    recs.append({"t": "event", "name": "sim_prediction", "ts": 1.4,
                 "attrs": {"predicted_step_ms": 9.0, "num_devices": 8,
                           "batch_size": 64, "compute_dtype": "bfloat16"}})
    durs = [2.0, 0.010, 0.012, 0.011, 0.010, 0.010, 0.050, 0.011]
    ts = 2.0
    for i, d in enumerate(durs):
        recs.append({"t": "span", "name": "data_wait", "id": 100 + i,
                     "parent": None, "ts": round(ts - 0.001, 6),
                     "dur": 0.001, "attrs": {"batch_size": 64}})
        recs.append({"t": "span", "name": "step", "id": 2 + i,
                     "parent": None, "ts": round(ts, 6), "dur": d,
                     "attrs": {"step": i, "first": i == 0,
                               "batch_size": 64}})
        ts += d + 0.002
    recs.append({"t": "event", "name": "health", "ts": 2.1,
                 "attrs": {"kind": "nonfinite_loss", "step": 4, "count": 2,
                           "window_steps": 2}})
    recs.append({"t": "event", "name": "health", "ts": 2.25,
                 "attrs": {"kind": "straggler", "step": 6, "dur_ms": 50.0,
                           "p50_ms": 10.5, "ratio": 4.76,
                           "attribution": "data_wait"}})
    recs.append({"t": "event", "name": "health", "ts": 2.3,
                 "attrs": {"kind": "data_starvation", "step": 7,
                           "wait_s": 0.02, "step_s": 0.05, "ratio": 0.4,
                           "threshold": 0.3}})
    recs.append({"t": "event", "name": "sim_divergence", "ts": 2.4,
                 "attrs": {"scope": "step", "predicted_ms": 9.0,
                           "measured_ms": 10.75, "ratio": 0.8372,
                           "n_steps": 7}})
    for op, which, p, m, src in [
            ("conv1", "forward", 1.2, 1.5, "measured"),
            ("conv1", "backward", 2.4, 3.0, "measured"),
            ("dense1", "forward", 0.4, 0.1, "analytic"),
            ("dense1", "backward", 0.8, 0.9, "analytic")]:
        recs.append({"t": "event", "name": "sim_divergence", "ts": 3.0,
                     "attrs": {"scope": "op", "op": op, "which": which,
                               "predicted_ms": p, "measured_ms": m,
                               "ratio": round(p / m, 4), "src": src}})
    # FF_OPPROF in-training attribution: a cadence pass over two ops,
    # with the matching measured-provenance agreement row for one
    recs.append({"t": "event", "name": "sim_divergence", "ts": 3.5,
                 "attrs": {"scope": "op", "op": "dense2",
                           "which": "forward", "predicted_ms": 0.6,
                           "measured_ms": 0.5, "ratio": 1.2,
                           "src": "analytic", "measured_src": "opprof"}})
    for op, which, m, p in [("dense2", "forward", 0.5, 0.6),
                            ("dense2", "backward", 1.4, 1.2),
                            ("sm", "forward", 0.05, 0.04)]:
        recs.append({"t": "event", "name": "op_runtime", "ts": 3.5,
                     "attrs": {"op": op, "which": which,
                               "measured_ms": m, "predicted_ms": p,
                               "ratio": round(p / m, 4),
                               "src": "analytic", "step": 4}})
    recs.append({"t": "event", "name": "op_runtime_pass", "ts": 3.6,
                 "attrs": {"step": 4, "ops_measured": 2, "ops_total": 6,
                           "elapsed_s": 0.42}})
    recs.append({"t": "event", "name": "bench_phase", "ts": 0.0,
                 "attrs": {"phase": "preflight"}})
    recs.append({"t": "event", "name": "bench_phase", "ts": 1.9,
                 "attrs": {"phase": "alexnet"}})
    return recs


def write_trace(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_report_sections(tmp_path):
    path = str(tmp_path / "t.jsonl")
    write_trace(path, synthetic_records())
    report = health_report.main([path, "-o", str(tmp_path / "r.md")])
    assert os.path.exists(tmp_path / "r.md")
    for section in ["## Health findings", "## Step health",
                    "## Data pipeline",
                    "## Simulator agreement (predicted vs measured)",
                    "## Op runtime (in-training attribution)",
                    "## Last phase"]:
        assert section in report, f"missing {section}"
    # agreement rows carry both sides' provenance
    assert "| measured | standalone |" in report
    assert "| analytic | opprof |" in report
    assert "cadence coverage: 1 passes, 2 op measurements" in report
    assert "nonfinite_loss" in report
    assert "straggler" in report and "data_wait" in report
    # the straggler (4.76x) beats the op-table worst (dense1 4.00x)
    assert "worst 4.8x p50" in report
    assert "worst-case ratio: 4.00x off (dense1 forward)" in report
    assert "per-op ratio band: 0.80x – 4.00x" in report


def test_report_without_health_monitor_derives_step_row(tmp_path):
    """Trace with sim_prediction but no health events (FF_HEALTH off):
    the step-level agreement row is derived from the step spans."""
    recs = [r for r in synthetic_records()
            if not (r.get("name") in ("health", "sim_divergence"))]
    path = str(tmp_path / "t.jsonl")
    write_trace(path, recs)
    report = health_report.render_report(health_report.parse_trace(path))
    assert "- step: predicted 9.000 ms" in report
    assert "no health findings" in report


def test_empty_trace(tmp_path):
    path = str(tmp_path / "e.jsonl")
    write_trace(path, [])
    report = health_report.main([path])
    assert "no health findings" in report


def test_golden_output(tmp_path):
    """Byte-exact golden: regenerate with
    ``python tests/test_health.py --regen`` after deliberate format
    changes."""
    path = str(tmp_path / "t.jsonl")
    write_trace(path, synthetic_records())
    report = health_report.render_report(health_report.parse_trace(path))
    with open(GOLDEN) as f:
        assert report == f.read()


if __name__ == "__main__" and "--regen" in sys.argv:
    import tempfile

    tmp = os.path.join(tempfile.mkdtemp(), "t.jsonl")
    write_trace(tmp, synthetic_records())
    os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
    with open(GOLDEN, "w") as f:
        f.write(health_report.render_report(health_report.parse_trace(tmp)))
    print(f"regenerated {GOLDEN}")
