"""Observability package tests: span nesting + JSONL serialization,
counter aggregation, zero work when disabled, and end-to-end step
records from a real (CPU) training loop under FF_TELEMETRY=1."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, ".")

import flexflow_tpu as ff
from flexflow_tpu.observability import events


@pytest.fixture(autouse=True)
def _isolated_singleton(monkeypatch):
    """Each test gets a fresh process-wide log and a clean env."""
    monkeypatch.delenv("FF_TELEMETRY", raising=False)
    monkeypatch.delenv("FF_TELEMETRY_FILE", raising=False)
    events.reset_active()
    yield
    events.reset_active()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# EventLog unit tests
# ---------------------------------------------------------------------------

def test_spans_nest_and_serialize(tmp_path):
    ticks = iter(float(i) for i in range(1000))
    log = events.EventLog(str(tmp_path / "t.jsonl"), run_id="r1",
                          clock=lambda: next(ticks))
    with log.span("outer", kind="a"):
        with log.span("inner"):
            pass
    log.close()

    recs = _read_jsonl(log.path)  # every line must be valid JSON
    assert recs[0]["t"] == "meta" and recs[0]["run_id"] == "r1"
    spans = {r["name"]: r for r in recs if r["t"] == "span"}
    assert set(spans) == {"outer", "inner"}
    # inner closes first but records its parent's id
    assert spans["inner"]["parent"] == spans["outer"]["id"]
    assert spans["outer"]["parent"] is None
    assert spans["outer"]["dur"] > spans["inner"]["dur"] > 0
    assert spans["outer"]["attrs"] == {"kind": "a"}


def test_span_attrs_added_inside_body(tmp_path):
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    with log.span("work") as at:
        at["result"] = 42
    log.close()
    (span,) = [r for r in _read_jsonl(log.path) if r["t"] == "span"]
    assert span["attrs"] == {"result": 42}


def test_counters_aggregate(tmp_path):
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    log.counter("samples", 32.0)
    log.counter("samples", 32.0)
    log.counter("other", 1.0)
    log.close()
    assert log.totals == {"samples": 64.0, "other": 1.0}
    recs = [r for r in _read_jsonl(log.path) if r["t"] == "counter"]
    # each record carries the running total (truncation-safe aggregates)
    assert [r["total"] for r in recs if r["name"] == "samples"] == [32.0, 64.0]


def test_broken_observer_detached_once_under_concurrent_emit(
        tmp_path, capsys):
    import threading

    log = events.EventLog(str(tmp_path / "t.jsonl"))
    healthy = []
    log.add_observer(healthy.append)

    def boom(rec):
        raise RuntimeError("observer bug")

    log.add_observer(boom)
    n_threads, n_recs = 8, 50
    barrier = threading.Barrier(n_threads)

    def writer(i):
        barrier.wait()   # all threads hit the broken observer together
        for j in range(n_recs):
            log.event("tick", worker=i)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()

    # exactly one thread won the detach race and warned — not 8, not 400
    err = capsys.readouterr().err
    assert err.count("flexflow_tpu: telemetry observer") == 1
    assert "RuntimeError" in err
    assert boom not in log._observers
    # records kept flowing: to the sink AND to the surviving observer
    ticks = [r for r in _read_jsonl(log.path) if r.get("name") == "tick"]
    assert len(ticks) == n_threads * n_recs
    assert sum(r.get("name") == "tick" for r in healthy) \
        == n_threads * n_recs


def test_lazy_open_no_file_without_records(tmp_path):
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    assert not os.path.exists(log.path)  # constructing never touches disk
    log.close()
    assert not os.path.exists(log.path)


def test_active_log_disabled_by_default():
    assert events.active_log() is None


def test_for_config_env_and_flag(tmp_path, monkeypatch):
    assert events.for_config(ff.FFConfig()) is None
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(tmp_path / "e.jsonl"))
    log = events.for_config(ff.FFConfig())
    assert log is not None and log.path == str(tmp_path / "e.jsonl")
    events.reset_active()
    monkeypatch.delenv("FF_TELEMETRY")
    monkeypatch.delenv("FF_TELEMETRY_FILE")
    cfg = ff.FFConfig(telemetry=True, telemetry_file=str(tmp_path / "c.jsonl"))
    log = events.for_config(cfg)
    assert log is not None and log.path == str(tmp_path / "c.jsonl")


def test_config_cli_flags():
    cfg = ff.FFConfig()
    rest = cfg.parse_args(["--telemetry-file", "/tmp/x.jsonl", "--extra"])
    assert cfg.telemetry and cfg.telemetry_file == "/tmp/x.jsonl"
    assert rest == ["--extra"]


# ---------------------------------------------------------------------------
# training-loop integration
# ---------------------------------------------------------------------------

def _tiny_model(batch=16):
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="float32")
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 8), nchw=False)
    t = m.dense(inp, 16, activation=ff.ActiMode.RELU)
    m.softmax(m.dense(t, 4))
    return m, inp


def _train_steps(m, inp, steps):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((m.config.batch_size * steps, 8), np.float32)
    y = rng.integers(0, 4, (m.config.batch_size * steps, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()


def test_disabled_zero_event_log_calls(devices, tmp_path, monkeypatch):
    """Telemetry off: no trace file anywhere and literally zero event-log
    calls on the hot path (any write would raise)."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(
        events.EventLog, "_write",
        lambda self, rec: (_ for _ in ()).throw(
            AssertionError(f"event-log call while disabled: {rec}")))
    m, inp = _tiny_model()
    m.compile(ff.SGDOptimizer(lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    assert m._telemetry is None and m._stepstats is None
    m.init_layers()
    _train_steps(m, inp, 3)
    m.get_metrics()
    assert not os.path.exists("ff_trace.jsonl")


def test_train_iteration_emits_step_records(devices, tmp_path, monkeypatch):
    trace = tmp_path / "run.jsonl"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    m, inp = _tiny_model()
    m.compile(ff.SGDOptimizer(lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    assert m._telemetry is not None and m._stepstats is not None
    m.init_layers()
    _train_steps(m, inp, 3)
    m.get_metrics()
    events.reset_active()

    recs = _read_jsonl(str(trace))
    by_name = {}
    for r in recs:
        if r["t"] == "span":
            by_name.setdefault(r["name"], []).append(r)
    assert len(by_name["compile"]) == 1
    steps = by_name["step"]
    assert len(steps) == 3
    assert steps[0]["attrs"]["first"] and not steps[1]["attrs"]["first"]
    for s in steps:
        assert s["dur"] > 0
        assert s["attrs"]["samples_per_sec"] > 0
        assert s["attrs"]["mfu"] >= 0
    assert len(by_name["data_wait"]) == 3
    assert by_name["metric_drain"]
    gauges = {r["name"] for r in recs if r["t"] == "gauge"}
    assert {"samples_per_sec", "mfu", "first_step_wall_s",
            "est_collective_bytes_per_step"} <= gauges
    counters = [r for r in recs if r["t"] == "counter"
                and r["name"] == "samples"]
    assert counters[-1]["total"] == 3 * m.config.batch_size


def test_checkpoint_spans(devices, tmp_path, monkeypatch):
    trace = tmp_path / "run.jsonl"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    m, inp = _tiny_model()
    m.compile(ff.SGDOptimizer(lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers()
    _train_steps(m, inp, 1)
    ckpt = str(tmp_path / "ckpt.npz")
    m.save(ckpt)
    m.load(ckpt)
    events.reset_active()
    names = {r["name"] for r in _read_jsonl(str(trace)) if r["t"] == "span"}
    assert {"checkpoint_save", "checkpoint_restore"} <= names


def test_search_progress_events(devices, tmp_path, monkeypatch):
    trace = tmp_path / "run.jsonl"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    from flexflow_tpu.simulator.search import mcmc_search

    m, _ = _tiny_model()
    m.machine = None
    m.config.workers_per_node = 4
    m.config.num_nodes = 1
    # compile resolves machine; run the search standalone like compile does
    m.compile(ff.SGDOptimizer(lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    mcmc_search(m, budget=5, verbose=False)
    events.reset_active()
    recs = _read_jsonl(str(trace))
    assert any(r["t"] == "event" and r["name"] == "search_progress"
               for r in recs)
    assert any(r["t"] == "span" and r["name"] == "mcmc_search"
               for r in recs)
