"""Python-native example scripts as integration tests (reference:
python/test.sh runs every native example; SURVEY.md §4.1 — examples ARE
the reference's test suite)."""

import sys

import pytest

sys.path.insert(0, ".")


def test_mnist_mlp():
    from examples.native.mnist_mlp import top_level_task

    assert top_level_task(["-e", "2", "-b", "64"], num_samples=512) >= 60.0


@pytest.mark.slow
def test_mnist_mlp_attach():
    from examples.native.mnist_mlp_attach import top_level_task

    assert top_level_task(["-e", "2", "-b", "64"], num_samples=512) >= 60.0


@pytest.mark.slow
def test_mnist_cnn():
    from examples.native.mnist_cnn import top_level_task

    assert top_level_task(["-e", "2", "-b", "64"], num_samples=512) >= 60.0


@pytest.mark.slow
def test_cifar10_cnn():
    from examples.native.cifar10_cnn import top_level_task

    assert top_level_task(["-b", "64"], num_samples=512, epochs=4) >= 30.0


@pytest.mark.slow
def test_cifar10_cnn_attach():
    from examples.native.cifar10_cnn_attach import top_level_task

    assert top_level_task(["-b", "64"], num_samples=512, epochs=4) >= 30.0


@pytest.mark.slow
def test_cifar10_cnn_concat():
    from examples.native.cifar10_cnn_concat import top_level_task

    assert top_level_task(["-b", "64"], num_samples=512, epochs=4) >= 30.0


@pytest.mark.slow
def test_alexnet_torch_one_step_parity():
    from examples.native.alexnet_torch import top_level_task

    top_level_task([])


def test_print_layers():
    from examples.native.print_layers import top_level_task

    assert top_level_task(["-b", "8"]) == 5


def test_print_input():
    from examples.native.print_input import top_level_task

    assert top_level_task([])


def test_tensor_attach():
    from examples.native.tensor_attach import top_level_task

    assert top_level_task([])


@pytest.mark.slow
def test_alexnet_new_v2_api():
    from examples.native.alexnet_new import top_level_task

    top_level_task(["-b", "8"], iters=1)
