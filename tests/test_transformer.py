"""Transformer LM: attention op, LayerNorm, and seq-parallel strategies.

Covers the long-context path end to end: the MultiHeadAttention op under
pure data parallelism must match the same graph under a hybrid
(dp × sp) sequence-parallel strategy, and the model must train.
"""

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.config import ParallelConfig
from flexflow_tpu.models.transformer import build_transformer

B, S, E, HEADS, V = 8, 32, 32, 4, 64


def _build(cfg):
    m = ff.FFModel(cfg)
    tok, pos, out = build_transformer(m, cfg.batch_size, seq_length=S,
                                      num_layers=2, embed_dim=E,
                                      num_heads=HEADS, vocab_size=V)
    m.compile(ff.SGDOptimizer(lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    return m, tok, pos


def _batch(rng):
    toks = rng.integers(0, V, size=(B, S)).astype(np.int32)
    pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S)).copy()
    labels = np.roll(toks, -1, axis=1).astype(np.int32)
    return toks, pos, labels


def test_transformer_dp_vs_seq_parallel_same_forward(devices):
    rng = np.random.default_rng(0)
    toks, pos_arr, labels = _batch(rng)

    outs = {}
    for mode, strat in (("dp", None), ("sp", (2, 4, 1))):
        cfg = ff.FFConfig(batch_size=B, compute_dtype="float32")
        if strat is not None:
            for i in range(2):
                cfg.strategies[f"attn_{i}"] = ParallelConfig(
                    dims=strat, device_ids=tuple(range(8)))
        m, tok, pos = _build(cfg)
        m.init_layers(seed=0)
        if strat is not None:
            attn = next(op for op in m.ops if op.name == "attn_0")
            assert attn.pc.dims == strat
        m.set_batch({tok: toks, pos: pos_arr}, labels)
        m.eval_batch()
        _, probs = m._eval_step_fn(m._params, m._stats, m._batch)
        outs[mode] = np.asarray(probs)
    np.testing.assert_allclose(outs["dp"], outs["sp"], atol=2e-4)


def test_transformer_trains(devices):
    cfg = ff.FFConfig(batch_size=B, compute_dtype="float32")
    for i in range(2):
        cfg.strategies[f"attn_{i}"] = ParallelConfig(
            dims=(2, 4, 1), device_ids=tuple(range(8)))
    m, tok, pos = _build(cfg)
    m.init_layers(seed=1)
    rng = np.random.default_rng(1)
    toks, pos_arr, _ = _batch(rng)
    labels = np.broadcast_to(np.arange(S, dtype=np.int32) % V, (B, S)).copy()

    losses = []
    for _ in range(30):
        m.set_batch({tok: toks, pos: pos_arr}, labels)
        m.train_iteration()
        m.sync()
        m.get_metrics()
        losses.append(m.last_loss)
        m.reset_metrics()
    assert losses[-1] < losses[0] * 0.5, losses


@pytest.mark.slow
def test_transformer_4d_example(devices):
    """dp x sp x tp x ep in one graph (examples/transformer_4d.py)."""
    from examples.transformer_4d import top_level_task

    tokens_s = top_level_task([], seq=16, layers=2, dim=32, heads=4,
                              vocab=64, iters=2)
    assert tokens_s > 0


def test_generate_matches_full_forward_oracle(devices):
    """kv-cached jitted generate() == iterative full-forward argmax
    (the cache path and the training forward are numerically the same
    computation)."""
    import jax.numpy as jnp

    from flexflow_tpu.models.transformer import build_transformer

    S, V, B, P, N = 16, 50, 4, 5, 6
    cfg = ff.FFConfig(batch_size=B)
    m = ff.FFModel(cfg)
    tok, pos, _ = build_transformer(m, B, seq_length=S, num_layers=2,
                                    embed_dim=32, num_heads=4, vocab_size=V)
    m.compile(ff.SGDOptimizer(lr=0.01), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=11)

    rng = np.random.default_rng(3)
    prompt = rng.integers(0, V, size=(B, P)).astype(np.int32)
    out = m.generate(prompt, N)
    assert out.shape == (B, N)

    seq = prompt.copy()
    for _ in range(N):
        L = seq.shape[1]
        toks_full = np.zeros((B, S), np.int32)
        toks_full[:, :L] = seq
        posa = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S)).copy()
        env, _ = m._run_graph(m._params, m._stats,
                              {f"in_{tok.guid}": jnp.asarray(toks_full),
                               f"in_{pos.guid}": jnp.asarray(posa)},
                              False, None)
        probs = np.asarray(env[m.final_tensor().guid])
        nxt = probs[:, L - 1, :].argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq[:, P:])

    # sampled decoding: right shape/range, deterministic per seed
    s1 = m.generate(prompt, N, temperature=0.8, seed=5)
    s2 = m.generate(prompt, N, temperature=0.8, seed=5)
    np.testing.assert_array_equal(s1, s2)
    assert s1.shape == (B, N) and (s1 >= 0).all() and (s1 < V).all()


@pytest.mark.slow
def test_beam_search(devices):
    """beam_size=1 equals greedy generate; with K=V and N=2 the beam is
    exhaustive-optimal (verified by enumerating all V^2 continuations);
    eos freezing stops a finished beam's score."""
    import itertools

    import jax.numpy as jnp

    from flexflow_tpu.models.transformer import build_transformer

    S2, V2, B2, P2 = 12, 6, 3, 4
    cfg = ff.FFConfig(batch_size=B2)
    m = ff.FFModel(cfg)
    tok, pos, _ = build_transformer(m, B2, seq_length=S2, num_layers=2,
                                    embed_dim=16, num_heads=2,
                                    vocab_size=V2)
    m.compile(ff.SGDOptimizer(lr=0.01), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=21)
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, V2, size=(B2, P2)).astype(np.int32)

    g = m.generate(prompt, 3)
    seqs1, _ = m.beam_search(prompt, 3, beam_size=1)
    np.testing.assert_array_equal(seqs1[:, 0, :], g)

    N = 2
    seqs, scores = m.beam_search(prompt, N, beam_size=V2)
    assert (np.diff(scores, axis=1) <= 1e-6).all()  # best first

    def seq_logp(row, cont):
        seq = np.concatenate([prompt[row], np.asarray(cont, np.int32)])
        lp = 0.0
        for i, t in enumerate(cont):
            L = P2 + i
            tf = np.zeros((B2, S2), np.int32)
            tf[:, :len(seq)] = seq
            posa = np.broadcast_to(np.arange(S2, dtype=np.int32),
                                   (B2, S2)).copy()
            env, _ = m._run_graph(m._params, m._stats,
                                  {f"in_{tok.guid}": jnp.asarray(tf),
                                   f"in_{pos.guid}": jnp.asarray(posa)},
                                  False, None)
            p = np.asarray(env[m.final_tensor().guid])[row, L - 1, t]
            lp += np.log(p + 1e-30)
        return lp

    for row in range(B2):
        best = max(itertools.product(range(V2), repeat=N),
                   key=lambda c: seq_logp(row, c))
        assert tuple(seqs[row, 0, :].tolist()) == best
        np.testing.assert_allclose(scores[row, 0], seq_logp(row, best),
                                   rtol=1e-4, atol=1e-4)

    # eos freezing: a finished FINITE-score beam keeps emitting eos
    # (score -inf beams are fillers when every candidate is impossible
    # — their suffixes are arbitrary top_k tie-breaks)
    eos = int(seqs[0, 0, 0])
    seqs_e, scores_e = m.beam_search(prompt, 4, beam_size=2, eos_id=eos)
    checked = 0
    for row in range(B2):
        for k in range(2):
            if not np.isfinite(scores_e[row, k]):
                continue
            s = seqs_e[row, k].tolist()
            if eos in s:
                i = s.index(eos)
                assert all(t == eos for t in s[i:]), s
                checked += 1
    assert checked > 0


@pytest.mark.slow
def test_generate_on_sharded_model(devices):
    """generate/beam_search on a model trained over the 8-device mesh
    with head-TP attention: the decode jit consumes the sharded params
    directly (GSPMD computation-follows-data), no gather/resave step."""
    from flexflow_tpu.models.transformer import build_transformer
    from flexflow_tpu.parallel.mesh import Machine

    import jax

    B2, S2, V2 = 8, 16, 50
    cfg = ff.FFConfig(batch_size=B2, workers_per_node=8)
    for i in range(2):
        cfg.strategies[f"attn_{i}"] = ff.ParallelConfig(dims=(2, 1, 4))
    m = ff.FFModel(cfg)
    tok, pos, _ = build_transformer(m, B2, seq_length=S2, num_layers=2,
                                    embed_dim=32, num_heads=4,
                                    vocab_size=V2)
    m.compile(ff.SGDOptimizer(lr=0.01), "sparse_categorical_crossentropy",
              ["accuracy"], machine=Machine(jax.devices()))
    m.init_layers(seed=11)
    rng = np.random.default_rng(3)
    toks = rng.integers(0, V2, size=(B2, S2)).astype(np.int32)
    posa = np.broadcast_to(np.arange(S2, dtype=np.int32), (B2, S2)).copy()
    m.set_batch({tok: toks, pos: posa},
                np.roll(toks, -1, 1).astype(np.int32))
    m.train_iteration()
    m.sync()

    prompt = rng.integers(0, V2, size=(B2, 5)).astype(np.int32)
    out = m.generate(prompt, 4)
    assert out.shape == (B2, 4)
    seqs, scores = m.beam_search(prompt, 3, beam_size=2)
    assert seqs.shape == (B2, 2, 3)
    assert (np.diff(scores, axis=1) <= 1e-6).all()


@pytest.mark.slow
def test_beam_length_penalty_reranks(devices):
    """length_penalty re-ranks finished-short vs long beams by the GNMT
    normalization; raw scores stay untouched sums."""
    from flexflow_tpu.models.transformer import build_transformer

    S2, V2, B2, P2 = 12, 6, 2, 3
    cfg = ff.FFConfig(batch_size=B2)
    m = ff.FFModel(cfg)
    tok, pos, _ = build_transformer(m, B2, seq_length=S2, num_layers=1,
                                    embed_dim=16, num_heads=2,
                                    vocab_size=V2)
    m.compile(ff.SGDOptimizer(lr=0.01), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=3)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, V2, size=(B2, P2)).astype(np.int32)

    s0, sc0 = m.beam_search(prompt, 4, beam_size=3, eos_id=0)
    s1, sc1 = m.beam_search(prompt, 4, beam_size=3, eos_id=0,
                            length_penalty=1.0)
    # same beam SET per row, possibly re-ordered; normalized order holds
    for row in range(B2):
        assert {tuple(x) for x in s0[row]} == {tuple(x) for x in s1[row]}
        fin = np.isfinite(sc1[row])
        lens = np.where((s1[row] == 0).any(-1),
                        (s1[row] == 0).argmax(-1) + 1, 4)
        norm = sc1[row] / (((5.0 + lens) / 6.0) ** 1.0)
        assert (np.diff(norm[fin]) <= 1e-6).all()


@pytest.mark.slow
def test_generate_bfloat16(devices):
    """The bench's decode config: kv caches and activations in bf16
    (argmax over f32-cast probs keeps token selection stable)."""
    from flexflow_tpu.models.transformer import build_transformer

    cfg = ff.FFConfig(batch_size=4, compute_dtype="bfloat16")
    m = ff.FFModel(cfg)
    tok, pos, _ = build_transformer(m, 4, seq_length=16, num_layers=2,
                                    embed_dim=32, num_heads=4,
                                    vocab_size=50)
    m.compile(ff.SGDOptimizer(lr=0.01), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=2)
    prompt = np.random.default_rng(0).integers(
        0, 50, size=(4, 1)).astype(np.int32)
    out = m.generate(prompt, 8)
    assert out.shape == (4, 8) and (out >= 0).all() and (out < 50).all()


@pytest.mark.slow
def test_generate_top_k_top_p(devices):
    """top_k=1 sampling equals greedy for any temperature; top_p keeps
    sampled tokens inside the nucleus (checked against per-step
    full-forward distributions)."""
    from flexflow_tpu.models.transformer import build_transformer

    cfg = ff.FFConfig(batch_size=4)
    m = ff.FFModel(cfg)
    tok, pos, _ = build_transformer(m, 4, seq_length=16, num_layers=2,
                                    embed_dim=32, num_heads=4,
                                    vocab_size=20)
    m.compile(ff.SGDOptimizer(lr=0.01), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=9)
    prompt = np.random.default_rng(5).integers(
        0, 20, size=(4, 3)).astype(np.int32)

    greedy = m.generate(prompt, 6)
    k1 = m.generate(prompt, 6, temperature=1.7, top_k=1, seed=3)
    np.testing.assert_array_equal(k1, greedy)

    # nucleus: every sampled token must be at least as probable as the
    # nucleus cutoff of its step's distribution
    p = 0.5
    out = m.generate(prompt, 6, temperature=1.0, top_p=p, seed=11)
    import jax.numpy as jnp

    seq = prompt.copy()
    for i in range(6):
        L = seq.shape[1]
        tf = np.zeros((4, 16), np.int32)
        tf[:, :L] = seq
        posa = np.broadcast_to(np.arange(16, dtype=np.int32),
                               (4, 16)).copy()
        env, _ = m._run_graph(m._params, m._stats,
                              {f"in_{tok.guid}": jnp.asarray(tf),
                               f"in_{pos.guid}": jnp.asarray(posa)},
                              False, None)
        probs = np.asarray(env[m.final_tensor().guid])[:, L - 1, :]
        for row in range(4):
            srt = np.sort(probs[row])[::-1]
            keep_n = int((np.cumsum(srt) < p).sum())
            cutoff = srt[keep_n]
            assert probs[row, out[row, i]] >= cutoff - 1e-7
        seq = np.concatenate([seq, out[:, i:i + 1]], axis=1)


@pytest.mark.slow
def test_generate_compile_cache_reuse(devices):
    """New seeds/temperatures reuse the compiled decode scan (seed and
    temp are runtime arguments, not trace constants)."""
    from flexflow_tpu.models.transformer import build_transformer

    cfg = ff.FFConfig(batch_size=4)
    m = ff.FFModel(cfg)
    tok, pos, _ = build_transformer(m, 4, seq_length=16, num_layers=1,
                                    embed_dim=16, num_heads=2,
                                    vocab_size=20)
    m.compile(ff.SGDOptimizer(lr=0.01), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=1)
    prompt = np.random.default_rng(0).integers(
        0, 20, size=(4, 2)).astype(np.int32)
    for seed in range(3):
        m.generate(prompt, 3, temperature=0.7 + 0.1 * seed, seed=seed)
    assert len(m._gen_cache) == 1  # one sampled-scan executable
    m.generate(prompt, 3)          # greedy variant adds exactly one more
    assert len(m._gen_cache) == 2
