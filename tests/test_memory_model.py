"""Analytic per-device HBM model (simulator/memory.py) — the PREDICTED
view of the memory observatory — cross-checked against XLA's own
``compiled.memory_analysis()`` on the CPU backend, plus the pipeline
search's dominant-term rejection reasons and the provenance sidecar's
``hbm_per_device_bytes`` stamp."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, ".")

import flexflow_tpu as ff
from flexflow_tpu.observability import events
from flexflow_tpu.simulator.machine import TPUMachineModel
from flexflow_tpu.simulator.memory import (HBM_SAFETY, dominant_term,
                                           memory_per_device,
                                           optimizer_slots,
                                           weight_state_terms)

# Documented tolerance of the analytic model vs XLA's executable-level
# accounting: XLA fuses, rematerializes and reuses buffers, so the two
# legitimately differ — but on the reference models they agree within a
# factor of 2 (measured ratios: alexnet 0.97, transformer 0.87, DLRM
# 1.18 on jax 0.4.37 CPU).  A drift outside this band means the model
# (or an op's tile accounting) broke.
PRED_VS_XLA_BAND = 2.0


@pytest.fixture(autouse=True)
def _isolated_singleton(monkeypatch):
    monkeypatch.delenv("FF_TELEMETRY", raising=False)
    monkeypatch.delenv("FF_TELEMETRY_FILE", raising=False)
    monkeypatch.delenv("FF_MEMPLANE", raising=False)
    events.reset_active()
    yield
    events.reset_active()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# unit: term accounting
# ---------------------------------------------------------------------------

def test_optimizer_slots_mapping():
    m = ff.FFModel(ff.FFConfig(batch_size=4))
    assert optimizer_slots(None) == 1                       # search time
    assert optimizer_slots(ff.SGDOptimizer(lr=0.1)) == 0    # no momentum
    assert optimizer_slots(ff.SGDOptimizer(lr=0.1, momentum=0.9)) == 1
    assert optimizer_slots(ff.AdamOptimizer(m, alpha=1e-3)) == 2


def test_weight_state_terms_match_legacy_pipeline_budget():
    # the pipeline search budgeted 3 * 4 * w_elems (master + grad + one
    # slot); weight_state_terms(w, 1) must be numerically identical so
    # search decisions did not shift under the refactor
    w = 12345.0
    terms = weight_state_terms(w, opt_slots=1)
    assert sum(terms.values()) == 3.0 * 4.0 * w
    assert dominant_term({"params": 1.0, "activations": 5.0,
                          "staging": 2.0}) == "activations"


def test_data_parallel_replicates_weights_and_splits_activations(devices):
    m = ff.FFModel(ff.FFConfig(batch_size=16, workers_per_node=8))
    inp = m.create_tensor((16, 32), nchw=False)
    t = m.dense(inp, 64, name="fc")
    m.softmax(t, name="sm")
    mem = memory_per_device(m, machine_model=TPUMachineModel(num_devices=8))
    assert mem["num_devices"] == 8
    w_bytes = 4.0 * (32 * 64 + 64)  # kernel + bias, f32
    for row in mem["per_device"]:
        # every device holds the full (replicated) weight state...
        assert row["params"] == int(w_bytes)
        assert row["grads"] == int(w_bytes)
        # ...and a grad-sized ring-allreduce staging buffer
        assert row["staging"] >= int(w_bytes)
    # batch split 8-ways: per-device activations are 1/8 of the batch
    fc = mem["by_op"]["fc"]
    assert fc["dims"].startswith("8")
    assert mem["peak_bytes"] == mem["per_device"][mem["peak_device"]]["total"]
    assert mem["capacity_bytes"] > 0
    assert mem["headroom_bytes"] == mem["capacity_bytes"] - mem["peak_bytes"]
    assert mem["budget_bytes"] == int(HBM_SAFETY * mem["capacity_bytes"])


def test_host_sparse_embedding_occupies_no_hbm(devices):
    m = ff.FFModel(ff.FFConfig(batch_size=8, workers_per_node=1))
    inp = m.create_tensor((8, 4), dtype="int32", nchw=False)
    t = m.embedding(inp, 5000, 16, aggr="sum", name="emb")
    from flexflow_tpu.config import ParallelConfig
    host_pc = ParallelConfig.host_rowsparse(t.num_dims)
    mem = memory_per_device(m, strategies={"emb": host_pc})
    assert mem["by_op"]["emb"]["bytes"] == 0
    assert mem["by_op"]["emb"]["host"] is True


# ---------------------------------------------------------------------------
# predicted vs compiled.memory_analysis() — the cross-check the
# observatory exists for
# ---------------------------------------------------------------------------

def _train_one_step_with_plane(monkeypatch, tmp_path, build):
    trace = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", trace)
    monkeypatch.setenv("FF_MEMPLANE", "1")
    events.reset_active()
    m = build()
    m.sync()
    recs = _read_jsonl(trace)
    pred = [r for r in recs if r.get("name") == "memory_predicted"][-1]
    xla = [r for r in recs if r.get("name") == "xla_memory"
           and r["attrs"]["site"] == "train_step"][-1]
    return pred["attrs"], xla["attrs"]


def _assert_band(pred, xla):
    ratio = pred["peak_bytes"] / max(xla["total_bytes"], 1)
    assert 1.0 / PRED_VS_XLA_BAND <= ratio <= PRED_VS_XLA_BAND, (
        f"predicted {pred['peak_bytes']} vs XLA {xla['total_bytes']} "
        f"(ratio {ratio:.2f}) outside the documented "
        f"factor-of-{PRED_VS_XLA_BAND:g} band")


def test_predicted_tracks_xla_alexnet(devices, tmp_path, monkeypatch):
    def build():
        from flexflow_tpu.models.alexnet import build_alexnet
        m = ff.FFModel(ff.FFConfig(batch_size=8, workers_per_node=1))
        inp, _ = build_alexnet(m, 8)
        m.compile(ff.SGDOptimizer(lr=0.01),
                  "sparse_categorical_crossentropy", ["accuracy"])
        m.init_layers(seed=0)
        dl = ff.DataLoader.synthetic(m, inp, num_samples=8)
        dl.next_batch(m)
        m.train_iteration()
        return m

    pred, xla = _train_one_step_with_plane(monkeypatch, tmp_path, build)
    _assert_band(pred, xla)
    # weight state dominates alexnet at batch 8 (245M params vs 18 MiB
    # of activations)
    assert pred["dominant_term"] == "params"


def test_predicted_tracks_xla_transformer(devices, tmp_path, monkeypatch):
    def build():
        from flexflow_tpu.models.transformer import build_transformer
        m = ff.FFModel(ff.FFConfig(batch_size=4, workers_per_node=1))
        toks, pos, _ = build_transformer(m, 4, seq_length=32, num_layers=2,
                                         embed_dim=64, num_heads=4,
                                         vocab_size=1000)
        m.compile(ff.SGDOptimizer(lr=0.01),
                  "sparse_categorical_crossentropy", ["accuracy"])
        m.init_layers(seed=0)
        rng = np.random.default_rng(0)
        x = rng.integers(0, 1000, (4, 32), dtype=np.int32)
        p = np.tile(np.arange(32, dtype=np.int32), (4, 1))
        y = rng.integers(0, 1000, (4, 32), dtype=np.int32)
        dl = ff.DataLoader(m, {toks: x, pos: p}, y)
        dl.next_batch(m)
        m.train_iteration()
        return m

    pred, xla = _train_one_step_with_plane(monkeypatch, tmp_path, build)
    _assert_band(pred, xla)


def test_predicted_tracks_xla_dlrm(devices, tmp_path, monkeypatch):
    def build():
        from flexflow_tpu.models.dlrm import build_dlrm, synthetic_batch
        sizes = [100, 100, 50]
        m = ff.FFModel(ff.FFConfig(batch_size=16, workers_per_node=1))
        sparse_in, dense_in, _ = build_dlrm(
            m, 16, embedding_sizes=sizes, embedding_bag_size=2,
            sparse_feature_size=8, mlp_bot=[4, 16, 8],
            mlp_top=[32, 16, 1])
        m.compile(ff.SGDOptimizer(lr=0.05), "mean_squared_error",
                  ["mean_squared_error"])
        m.init_layers(seed=0)
        sparse, dense, labels = synthetic_batch(16, sizes, 2, 4)
        bi = {t: a for t, a in zip(sparse_in, sparse)}
        bi[dense_in] = dense
        dl = ff.DataLoader(m, bi, labels)
        dl.next_batch(m)
        m.train_iteration()
        return m

    pred, xla = _train_one_step_with_plane(monkeypatch, tmp_path, build)
    _assert_band(pred, xla)


# ---------------------------------------------------------------------------
# pipeline search: rejection names the dominant term
# ---------------------------------------------------------------------------

def test_pipeline_rejection_names_dominant_term(devices):
    from flexflow_tpu.simulator.cost_model import CostModel
    from flexflow_tpu.simulator.pipeline_search import cost_pipeline_plan

    cfg = ff.FFConfig(batch_size=32, workers_per_node=8)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((32, 64), nchw=False)
    t = inp
    for i in range(6):
        t = m.dense(t, 64, activation="relu", name=f"fc{i}")
    m.softmax(m.dense(t, 10, name="head"), name="sm")

    mm_small = TPUMachineModel(num_devices=8, hbm_capacity=1.2e5)
    cost = CostModel(mm_small, measure=False)
    reject = {}
    r = cost_pipeline_plan(m, mm_small, cost, S=4, dp=2, microbatches=16,
                           remat=False, reject_out=reject)
    assert r is None
    # the out-param names what blew the budget and by how much
    assert reject["reason"].startswith("hbm:")
    assert reject["reason"].split(":", 1)[1] in (
        "params", "grads", "optimizer", "activations", "staging")
    assert reject["mem_bytes"] > reject["budget_bytes"]
    assert reject["budget_bytes"] == int(HBM_SAFETY * 1.2e5)
    assert set(reject["terms"]) >= {"params", "grads", "optimizer",
                                    "activations"}


# ---------------------------------------------------------------------------
# provenance sidecar: hbm_per_device_bytes stamp
# ---------------------------------------------------------------------------

def test_sidecar_carries_hbm_per_device(devices):
    from flexflow_tpu.observability.searchtrace import build_provenance

    m = ff.FFModel(ff.FFConfig(batch_size=16, workers_per_node=8))
    inp = m.create_tensor((16, 8), nchw=False)
    t = m.dense(inp, 16, activation="relu", name="fc1")
    m.softmax(m.dense(t, 4, name="fc2"), name="sm")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy",
              ["accuracy"])
    prov = build_provenance(m, m._all_strategies(), engine="test",
                            budget=0, seed=0,
                            machine_model=TPUMachineModel(num_devices=8))
    hbm = prov["hbm_per_device_bytes"]
    assert isinstance(hbm, list) and len(hbm) == 8
    assert all(isinstance(b, int) and b >= 0 for b in hbm)
    assert prov["hbm_peak_bytes"] == max(hbm) > 0
    assert prov["hbm_dominant_term"] in ("params", "grads", "optimizer",
                                         "activations", "staging")
    assert prov["hbm_capacity_bytes"] > prov["hbm_peak_bytes"]
