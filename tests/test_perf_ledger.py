"""Perf-ledger tests: append/read durability, regression detection on
the (metric, backend, proxy, batch) groups, the report renderer, and the
CLI (docs/observability.md "The perf ledger")."""

import json
import sys

sys.path.insert(0, ".")

from flexflow_tpu.tools import perf_ledger as pl  # noqa: E402

METRIC = "alexnet_train_samples_per_sec_per_chip"


def _bench(value, status="ok", proxy=False, backend="tpu", t=0.0, **kw):
    e = {"kind": "bench", "metric": METRIC, "value": value, "unit":
         "samples/s/chip", "backend": backend, "proxy": proxy,
         "status": status, "unix_time": t}
    e.update(kw)
    return e


def test_append_read_roundtrip(tmp_path, monkeypatch):
    ledger = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("FF_PERF_LEDGER", str(ledger))
    stamped = pl.append_entry({"kind": "bench", "metric": METRIC,
                               "value": 100.0, "status": "ok"})
    # schema + wall time stamped on the way in (commit may be None
    # outside a checkout, but the key must exist)
    assert stamped["schema"] == pl.SCHEMA_VERSION
    assert stamped["unix_time"] > 0
    assert "commit" in stamped
    pl.append_entry({"kind": "bench", "metric": METRIC, "value": 90.0,
                     "status": "ok"})
    got = pl.read_entries()
    assert [e["value"] for e in got] == [100.0, 90.0]


def test_corrupt_line_skipped_and_append_recovers(tmp_path):
    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text(json.dumps(_bench(100.0)) + "\n"
                      + '{"kind": "bench", "val')  # killed mid-append
    assert len(pl.read_entries(str(ledger))) == 1
    # the next append must start a fresh line, not glue onto the stub
    pl.append_entry(_bench(95.0), path=str(ledger))
    got = pl.read_entries(str(ledger))
    assert [e["value"] for e in got] == [100.0, 95.0]


def test_read_entries_missing_file(tmp_path):
    assert pl.read_entries(str(tmp_path / "nope.jsonl")) == []


def test_regression_flags_20pct_drop():
    entries = [_bench(100.0, t=1.0), _bench(80.0, t=2.0)]
    regs = pl.detect_regressions(entries)
    assert len(regs) == 1
    assert regs[0]["drop_frac"] == 0.2
    assert regs[0]["prev_value"] == 100.0 and regs[0]["value"] == 80.0


def test_regression_ignores_small_drop_and_recovery():
    entries = [_bench(100.0, t=1.0), _bench(95.0, t=2.0),
               _bench(101.0, t=3.0)]
    assert pl.detect_regressions(entries) == []


def test_regression_groups_are_independent():
    # a cheap CPU proxy number must never read as a "regression" vs a
    # chip number, nor a different-batch run vs another batch
    entries = [_bench(100.0, t=1.0),
               _bench(5.0, t=2.0, proxy=True, backend="cpu"),
               _bench(100.0, t=3.0, batch=256),
               _bench(50.0, t=4.0, batch=1024)]
    assert pl.detect_regressions(entries) == []


def test_regression_skips_killed_and_zero_entries():
    # a watchdog kill (value 0) is an availability event, not a 100%
    # perf loss — and must not reset the comparison baseline either
    entries = [_bench(100.0, t=1.0),
               _bench(0.0, status="killed", t=2.0),
               _bench(99.0, t=3.0)]
    assert pl.detect_regressions(entries) == []


def test_last_good_skips_proxy_error_killed():
    entries = [_bench(100.0, t=1.0),
               _bench(0.0, status="killed", t=2.0),
               _bench(7.0, proxy=True, backend="cpu", t=3.0),
               _bench(0.0, status="error", t=4.0)]
    lg = pl.last_good(entries)
    assert lg is not None and lg["value"] == 100.0
    assert pl.last_good([_bench(5.0, proxy=True)]) is None


def test_report_renders_trajectory_and_regression(tmp_path):
    entries = [_bench(100.0, t=1.0, commit="aaa111"),
               _bench(75.0, t=2.0, commit="bbb222"),
               {"kind": "calibration", "backend": "tpu", "entries": 75,
                "fit_points": 52, "fit_log_rmse": 1.03, "unix_time": 3.0}]
    rep = pl.render_report(entries)
    assert "# Perf ledger" in rep
    assert "## Trajectory" in rep
    assert "**REGRESSION**" in rep
    assert "-25.0%" in rep
    assert "## Calibration sessions" in rep
    assert "bbb222" in rep


def test_cli_append_report_last_good(tmp_path, capsys):
    ledger = str(tmp_path / "ledger.jsonl")
    assert pl.main(["append", "--ledger", ledger,
                    "--json", json.dumps(_bench(123.0, t=5.0))]) == 0
    capsys.readouterr()
    assert pl.main(["last-good", "--ledger", ledger]) == 0
    assert json.loads(capsys.readouterr().out)["value"] == 123.0
    out_md = tmp_path / "report.md"
    assert pl.main(["report", "--ledger", ledger,
                    "-o", str(out_md)]) == 0
    assert "## Trajectory" in out_md.read_text()
    # empty ledger -> last-good rc 1
    assert pl.main(["last-good", "--ledger",
                    str(tmp_path / "empty.jsonl")]) == 1


def test_seed_ledger_is_parseable():
    # the committed PERF_LEDGER.jsonl (backfilled from BENCH_r01–r05)
    # must parse, carry the last good chip number, and show no spurious
    # regressions (r02 and the round-5 window ran different configs)
    import os

    path = os.path.join(pl.repo_root(), pl.LEDGER_BASENAME)
    entries = pl.read_entries(path)
    assert len(entries) >= 6
    lg = pl.last_good(entries)
    assert lg is not None and lg["value"] > 0
    assert pl.detect_regressions(entries) == []
