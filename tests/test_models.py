"""Model-zoo construction + one-train-step tests (tiny shapes, 8-dev mesh).

The reference validates models by running the example apps (SURVEY.md §4);
these tests build each zoo model, check key shapes against the reference
topology, and run a real fused train step.
"""

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.models.alexnet import build_alexnet
from flexflow_tpu.models.candle_uno import build_candle_uno
from flexflow_tpu.models.dlrm import build_dlrm, synthetic_batch as dlrm_batch
from flexflow_tpu.models.inception import build_inception_v3
from flexflow_tpu.models.nmt import build_nmt, synthetic_batch as nmt_batch
from flexflow_tpu.models.resnet import build_resnet50


def test_alexnet_topology(devices):
    m = ff.FFModel(ff.FFConfig(batch_size=4))
    inp, out = build_alexnet(m, 4)
    assert inp.dims == (4, 229, 229, 3)
    assert out.dims == (4, 10)
    assert len([o for o in m.ops if o._type == "Conv2D"]) == 5
    assert len([o for o in m.ops if o._type == "Dense"]) == 3


@pytest.mark.slow
def test_inception_topology(devices):
    m = ff.FFModel(ff.FFConfig(batch_size=2))
    inp, out = build_inception_v3(m, 2)
    assert inp.dims == (2, 299, 299, 3)
    assert out.dims == (2, 10)
    # reference inception has 11 modules; final spatial size 8x8 before pool
    pool_in = [o for o in m.ops if o._type == "Pool2D"][-1].inputs[0]
    assert pool_in.dims[1:3] == (8, 8)
    assert pool_in.dims[3] == 2048  # InceptionE output channels 320+384*4+192


@pytest.mark.slow
def test_resnet50_trains_one_step(devices):
    m = ff.FFModel(ff.FFConfig(batch_size=8))
    inp, out = build_resnet50(m, 8, height=64, width=64)
    assert out.dims == (8, 10)
    m.compile(ff.SGDOptimizer(lr=0.001), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers()
    dl = ff.DataLoader.synthetic(m, inp, num_samples=8)
    dl.next_batch(m)
    m.train_iteration()
    m.sync()
    pm = m.get_metrics()
    assert pm.train_all == 8


def test_dlrm_trains(devices):
    sizes = [100, 100, 50]
    m = ff.FFModel(ff.FFConfig(batch_size=16))
    sparse_in, dense_in, out = build_dlrm(
        m, 16, embedding_sizes=sizes, embedding_bag_size=2,
        sparse_feature_size=8, mlp_bot=[4, 16, 8], mlp_top=[32, 16, 1])
    assert out.dims == (16, 1)
    m.compile(ff.SGDOptimizer(lr=0.05), "mean_squared_error",
              ["accuracy", "mean_squared_error"])
    m.init_layers()
    sparse, dense, labels = dlrm_batch(16, sizes, 2, 4)
    batch_inputs = {t: a for t, a in zip(sparse_in, sparse)}
    batch_inputs[dense_in] = dense
    losses = []
    for step in range(20):
        m.set_batch(batch_inputs, labels)
        m.train_iteration()
        if step % 19 == 0:
            m._drain_metrics()
            losses.append(m.last_loss)
    assert losses[-1] < losses[0], f"DLRM loss did not decrease: {losses}"


def test_nmt_trains(devices):
    vocab, seq, bs = 64, 6, 8
    m = ff.FFModel(ff.FFConfig(batch_size=bs))
    src, dst, out = build_nmt(m, bs, seq_length=seq, num_layers=2,
                              hidden_size=16, embed_size=16, vocab_size=vocab)
    assert out.dims == (bs, seq, vocab)
    # embed_dst shares embed_src's table — one weight set only
    embeds = [o for o in m.ops if o._type == "Embedding"]
    assert embeds[1].share_from is embeds[0]
    m.compile(ff.AdamOptimizer(alpha=0.01), "sparse_categorical_crossentropy",
              ["accuracy", "sparse_categorical_crossentropy"])
    m.init_layers()
    assert m.label_tensor.dims == (bs, seq)
    s, d, labels = nmt_batch(bs, seq, vocab)
    labels = d  # learnable task: predict the decoder input itself
    losses = []
    for step in range(30):
        m.set_batch({src: s, dst: d}, labels)
        m.train_iteration()
    m._drain_metrics()
    pm = m.get_metrics()
    acc = pm.accuracy
    assert acc > 50.0, f"NMT failed to learn copy task: acc={acc}"


@pytest.mark.slow
def test_candle_uno_builds(devices):
    m = ff.FFModel(ff.FFConfig(batch_size=4))
    inputs, out = build_candle_uno(m, 4, dense_layers=[32] * 3,
                                   dense_feature_layers=[32] * 3)
    assert out.dims == (4, 1)
    assert len(inputs) == 5
    m.compile(ff.SGDOptimizer(lr=0.01), "mean_squared_error",
              ["mean_squared_error"])
    m.init_layers()
    rng = np.random.default_rng(0)
    batch = {t: rng.standard_normal((4, t.dims[1]), dtype=np.float32)
             for t in inputs.values()}
    m.set_batch(batch, rng.standard_normal((4, 1), dtype=np.float32))
    m.train_iteration()
    m.sync()


@pytest.mark.slow
def test_nmt_greedy_translate_matches_teacher_forced_oracle(devices):
    """LSTM decode carry (seeded from the encoder state at step 0) must
    reproduce the teacher-forced full-forward argmax chain."""
    import jax.numpy as jnp

    from flexflow_tpu.models.nmt import build_nmt, greedy_translate

    B, S, V = 4, 10, 40
    cfg = ff.FFConfig(batch_size=B)
    m = ff.FFModel(cfg)
    src, dst, _ = build_nmt(m, B, seq_length=S, num_layers=2,
                            hidden_size=32, embed_size=24, vocab_size=V)
    m.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=13)

    rng = np.random.default_rng(2)
    src_toks = rng.integers(0, V, size=(B, S)).astype(np.int32)
    N = 6
    out = greedy_translate(m, src, dst, src_toks, N, bos_id=1)
    assert out.shape == (B, N)

    # oracle: iterative teacher-forced full forward over the dst prefix
    seq = np.full((B, 1), 1, np.int32)
    for _ in range(N):
        L = seq.shape[1]
        dst_full = np.zeros((B, S), np.int32)
        dst_full[:, :L] = seq
        env, _ = m._run_graph(m._params, m._stats,
                              {f"in_{src.guid}": jnp.asarray(src_toks),
                               f"in_{dst.guid}": jnp.asarray(dst_full)},
                              False, None)
        probs = np.asarray(env[m.final_tensor().guid])  # (B, S, V)
        nxt = probs[:, L - 1, :].argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq[:, 1:])
