"""DeltaSimulator equality tests.

The delta simulator's contract is BITWISE equality with the full
rebuild (delta.py module docstring): same strategies in, same float
out, for every proposal — not "close", identical.  These tests pin
that over random proposal sequences on several model graphs (including
host-rowsparse embedding placements and both weight-sync modes), and
pin that a seeded mcmc_search returns an identical SearchResult with
FF_SIM_DELTA on and off.
"""

import random

import pytest

from flexflow_tpu.config import ParallelConfig
from flexflow_tpu.simulator.cost_model import CostModel
from flexflow_tpu.simulator.delta import DeltaSimulator
from flexflow_tpu.simulator.machine import TPUMachineModel
from flexflow_tpu.simulator.search import mcmc_search, random_parallel_config
from flexflow_tpu.simulator.simulator import Simulator
from flexflow_tpu.tools.offline_search import build_model


def _setup(name, nd, overlap):
    model = build_model(name, 64, nd)
    mm = TPUMachineModel.calibrated(num_devices=nd)
    sim = Simulator(mm, CostModel(mm, measure=False))
    sim.overlap = overlap
    dp = {op.name: ParallelConfig.data_parallel(op.output.num_dims, nd)
          .with_device_ids(tuple(range(nd)))
          for op in model.ops}
    return model, sim, dp


def _drive(model, sim, dp, nd, proposals, seed):
    """Random propose/commit/rollback walk asserting exact equality of
    every delta cost against a from-scratch simulate_runtime."""
    delta = DeltaSimulator(sim, model)
    assert delta.reset(dp) == sim.simulate_runtime(model, dp)
    cur = dict(dp)
    rng = random.Random(seed)
    ops = [op for op in model.ops if op.weights or op.inputs]
    for _ in range(proposals):
        op = rng.choice(ops)
        pc = op.legalize_pc(random_parallel_config(op, nd, rng, model=model))
        trial = dict(cur)
        trial[op.name] = pc
        assert delta.propose(op.name, pc) == sim.simulate_runtime(model, trial)
        if rng.random() < 0.4:
            delta.commit()
            cur = trial
        else:
            delta.rollback()
    # the committed state survived the walk intact
    assert delta.reset(cur) == sim.simulate_runtime(model, cur)


# 5 cases x 45 proposals = 225 random proposals per suite run, plus the
# dedicated host-rowsparse walk below.
CASES = [
    ("alexnet", 16, False),
    ("alexnet", 16, True),   # overlap_backward_update
    ("dlrm", 8, False),      # embeddings (host-rowsparse reachable)
    ("dlrm", 8, True),
    ("transformer", 8, False),
]


@pytest.mark.parametrize("name,nd,overlap", CASES)
def test_delta_matches_full_exactly(name, nd, overlap):
    model, sim, dp = _setup(name, nd, overlap)
    _drive(model, sim, dp, nd, proposals=45, seed=12345)


def test_delta_host_rowsparse_embedding():
    """Forced host placement: embeddings move to the host (and back),
    which rewrites node devices, kills comm tasks on incident edges,
    and drops the update fragment — the deepest single-op rewrite."""
    model, sim, dp = _setup("dlrm", 8, False)
    delta = DeltaSimulator(sim, model)
    delta.reset(dp)
    embs = [op for op in model.ops if op._type == "Embedding"]
    assert embs, "dlrm zoo model lost its embeddings"
    cur = dict(dp)
    for op in embs:
        pc = op.legalize_pc(ParallelConfig.host_rowsparse(op.output.num_dims))
        trial = dict(cur)
        trial[op.name] = pc
        assert delta.propose(op.name, pc) == sim.simulate_runtime(model, trial)
        delta.commit()
        cur = trial
    # and back off-host again
    op = embs[0]
    pc = op.legalize_pc(ParallelConfig.data_parallel(op.output.num_dims, 8)
                        .with_device_ids(tuple(range(8))))
    trial = dict(cur)
    trial[op.name] = pc
    assert delta.propose(op.name, pc) == sim.simulate_runtime(model, trial)
    delta.rollback()
    assert delta.propose(op.name, pc) == sim.simulate_runtime(model, trial)


def test_delta_python_fallback_matches(monkeypatch):
    """With the native event engine unavailable, the Python heap
    fallbacks of both engines must still agree exactly."""
    import flexflow_tpu.utils.native as native

    monkeypatch.setattr(native, "sim_lib", lambda: None)
    model, sim, dp = _setup("alexnet", 16, False)
    _drive(model, sim, dp, 16, proposals=12, seed=99)


def _search(delta_on, monkeypatch, budget=150, seed=3):
    monkeypatch.setenv("FF_SIM_DELTA", "1" if delta_on else "0")
    model = build_model("alexnet", 64, 16)
    mm = TPUMachineModel.calibrated(num_devices=16)
    return mcmc_search(model, budget=budget, machine_model=mm,
                       seed=seed, verbose=False)


def test_mcmc_identical_with_delta_on_off(monkeypatch):
    """Seeded search is bit-for-bit reproducible across engines: same
    strategy map, same best/dp costs — only the throughput telemetry
    may differ."""
    a = _search(True, monkeypatch)
    b = _search(False, monkeypatch)
    assert dict(a) == dict(b)
    assert a.best_s == b.best_s
    assert a.dp_s == b.dp_s
    assert a.delta_sim is True
    assert b.delta_sim is False
    assert a.proposals_per_s > 0 and b.proposals_per_s > 0
