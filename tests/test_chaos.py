"""Chaos fault injection + step-level recovery (testing/chaos.py,
runtime/resilience.py).

Beyond the reference (strictly fail-stop, nothing checkpointed — SURVEY
§5.3/§5.4): every recovery path is exercised by a seeded, deterministic
fault and asserted bitwise — an injected NaN step leaves params
bit-identical and training converges anyway; a SIGTERM mid-epoch saves
and the rerun matches the uninterrupted run exactly; a failing
checkpoint write is retried and never leaves a partial file.
"""

import glob
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.observability import events
from flexflow_tpu.runtime import resilience
from flexflow_tpu.runtime.elastic import elastic_train
from flexflow_tpu.runtime.resilience import (NonFiniteEscalationError,
                                             Preempted, with_ckpt_retries)
from flexflow_tpu.testing.chaos import (ChaosError, ChaosIOError,
                                        ChaosMonkey, from_env, parse_spec)


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for k in ("FF_CHAOS", "FF_CHAOS_SEED", "FF_SKIP_NONFINITE",
              "FF_CKPT_RETRIES", "FF_CKPT_BACKOFF_S", "FF_TELEMETRY",
              "FF_TELEMETRY_FILE", "FF_HEALTH"):
        monkeypatch.delenv(k, raising=False)
    events.reset_active()
    yield
    events.reset_active()


def _build(n_samples=48, seed=9):
    cfg = ff.FFConfig(batch_size=16)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False, name="input")
    t = m.dense(inp, 16, activation="relu", name="fc1")
    t = m.dense(t, 4, name="fc2")
    m.softmax(t, name="sm")
    m.compile(ff.AdamOptimizer(alpha=0.01),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=seed)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((n_samples, 8), dtype=np.float32)
    y = rng.integers(0, 4, size=(n_samples, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y, seed=5)
    return m, dl


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------

def test_parse_spec_grammar():
    exact, prob = parse_spec(
        "step:23=nan_loss;step:40=hang:2;ckpt_save:2=io_error;step:57=sigterm")
    assert exact[("step", 23)] == ("nan_loss", None)
    assert exact[("step", 40)] == ("hang", 2.0)
    assert exact[("ckpt_save", 2)] == ("io_error", None)
    assert exact[("step", 57)] == ("sigterm", None)
    assert prob == []

    exact, prob = parse_spec("data:p0.25=error")
    assert exact == {} and prob == [("data", 0.25, "error", None)]


@pytest.mark.parametrize("bad", [
    "nonsense", "step:=nan_loss", "badsite:1=nan_loss",
    "step:1=badfault", "step:px=error", "step:p1.5=error",
    "step:-1=error", "step:1=hang:soon", ";;",
])
def test_parse_spec_rejects_bad_entries(bad):
    with pytest.raises(ValueError):
        parse_spec(bad)


def test_exact_trigger_fires_once_and_prob_is_seeded():
    mk = ChaosMonkey("sync:2=error")
    assert mk.fire("sync") is None          # call 1
    with pytest.raises(ChaosError):
        mk.fire("sync")                     # call 2 fires
    assert mk.fire("sync") is None          # spent — never re-fires
    assert mk.fired == [("sync", 2, "error")]

    # probabilistic triggers are pure in (seed, site, index): two
    # monkeys with the same spec + seed fire on identical call indices
    def hit_indices():
        mk = ChaosMonkey("data:p0.2=error", seed=7)
        hits = []
        for i in range(200):
            try:
                mk.fire("data")
            except ChaosError:
                hits.append(i)
        return hits

    a, b = hit_indices(), hit_indices()
    assert a == b and 10 < len(a) < 80


def test_from_env_zero_cost_when_unset():
    assert from_env() is None
    m, _ = _build()
    assert m._chaos is None
    assert m._nonfinite_guard is None
    # no guard/health -> the metric vector carries only the base keys:
    # the train step compiles exactly as on an unchaosed build (no extra
    # entries, no select, no extra dispatches)
    assert m._metric_keys() == ["train_all", "train_correct", "cce_loss",
                                "sparse_cce_loss", "mse_loss", "rmse_loss",
                                "mae_loss", "loss", "steps"]


# ---------------------------------------------------------------------------
# NonFiniteGuard
# ---------------------------------------------------------------------------

def test_nan_step_is_skipped_bitwise_and_training_converges(
        monkeypatch, devices):
    monkeypatch.setenv("FF_CHAOS", "step:2=nan_loss")
    monkeypatch.setenv("FF_SKIP_NONFINITE", "5")
    m, dl = _build()
    losses = []
    for i in range(12):
        dl.next_batch(m)
        if i == 2:
            m.sync()
            pre = np.asarray(m.get_parameter("fc1", "kernel"))
        m.train_iteration()
        if i == 2:
            m.sync()
            post = np.asarray(m.get_parameter("fc1", "kernel"))
            # the poisoned step restored the PRE-step params bitwise
            assert (pre == post).all()
        m.get_metrics()
        if m.last_loss is not None:
            losses.append(m.last_loss)
    assert m._nonfinite_guard.total_skipped == 1
    assert m._chaos.fired == [("step", 2, "nan_loss")]
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0]  # training converged anyway


def test_persistent_nan_escalates(monkeypatch, devices):
    monkeypatch.setenv("FF_CHAOS",
                       "step:1=nan_loss;step:2=nan_loss;step:3=nan_loss")
    monkeypatch.setenv("FF_SKIP_NONFINITE", "3")
    m, dl = _build()
    with pytest.raises(NonFiniteEscalationError, match="3 consecutive"):
        for _ in range(6):
            dl.next_batch(m)
            m.train_iteration()
            m.get_metrics()


def test_consec_run_survives_metric_reset(monkeypatch, devices):
    # the escalation counter is a run length across drains AND resets
    monkeypatch.setenv("FF_CHAOS",
                       "step:1=nan_loss;step:2=nan_loss;step:3=nan_loss")
    monkeypatch.setenv("FF_SKIP_NONFINITE", "3")
    m, dl = _build()
    with pytest.raises(NonFiniteEscalationError):
        for _ in range(6):
            dl.next_batch(m)
            m.train_iteration()
            m.get_metrics()
            m.reset_metrics()  # an epoch boundary between every step


# ---------------------------------------------------------------------------
# retrying atomic checkpoint I/O
# ---------------------------------------------------------------------------

def test_ckpt_io_error_retried_no_partial_file(tmp_path, monkeypatch,
                                               devices):
    monkeypatch.setenv("FF_CHAOS", "ckpt_save:1=io_error")
    monkeypatch.setenv("FF_CKPT_BACKOFF_S", "0.01")
    m, _ = _build()
    path = str(tmp_path / "w.npz")
    m.save(path)  # attempt 1 fails, retry succeeds
    assert os.path.exists(path)
    assert not glob.glob(str(tmp_path / "*.tmp-*"))
    assert ("ckpt_save", 1, "io_error") in m._chaos.fired
    # the checkpoint is loadable (not truncated)
    m.load(path)


def test_ckpt_retries_exhausted_propagates(monkeypatch):
    calls = []

    def always_fails():
        calls.append(1)
        raise ChaosIOError("disk on fire")

    with pytest.raises(ChaosIOError):
        with_ckpt_retries(always_fails, retries=2, base_delay=0.0,
                          sleep=lambda s: None)
    assert len(calls) == 3  # 1 + 2 retries


def test_atomic_npz_failed_write_leaves_nothing(tmp_path, devices):
    from flexflow_tpu.runtime import checkpoint as ck
    m, _ = _build()
    real = np.savez

    def boom(f, **kw):
        real(f, **kw)  # bytes hit the temp file...
        raise OSError("disk full")  # ...then the write "fails"

    np.savez = boom
    try:
        with pytest.raises(OSError):
            ck._save_npz(m, str(tmp_path / "x.npz"))
    finally:
        np.savez = real
    assert os.listdir(tmp_path) == []  # no final, no temp


# ---------------------------------------------------------------------------
# telemetry narration
# ---------------------------------------------------------------------------

def test_recovery_events_reach_trace_and_reports(tmp_path, monkeypatch,
                                                 devices):
    trace = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", trace)
    monkeypatch.setenv("FF_CHAOS", "step:2=nan_loss;ckpt_save:1=io_error")
    monkeypatch.setenv("FF_SKIP_NONFINITE", "5")
    monkeypatch.setenv("FF_CKPT_BACKOFF_S", "0.01")
    events.reset_active()
    m, dl = _build()
    for _ in range(4):
        dl.next_batch(m)
        m.train_iteration()
    m.get_metrics()
    m.save(str(tmp_path / "w.npz"))
    m._telemetry.flush()
    events.reset_active()

    names = [json.loads(l).get("name") for l in open(trace) if l.strip()]
    assert "fault_injected" in names
    assert "step_skipped" in names
    assert "ckpt_retry" in names

    from flexflow_tpu.tools import health_report, trace_report
    rep = trace_report.main([trace, "-o", str(tmp_path / "r.md")])
    assert "## Resilience" in rep
    assert "nan_loss" in rep and "ckpt_retry" in rep
    hrep = health_report.main([trace, "-o", str(tmp_path / "h.md")])
    assert "## Recovery" in hrep
    assert "non-finite steps skipped: 1" in hrep


# ---------------------------------------------------------------------------
# preemption (in-process signal + real subprocess kill)
# ---------------------------------------------------------------------------

def test_sigterm_preemption_saves_then_resume_is_bitwise(
        tmp_path, monkeypatch, devices):
    # uninterrupted baseline: 2 epochs (6 steps)
    mb, dlb = _build()
    elastic_train(mb, dlb, epochs=2,
                  checkpoint_dir=str(tmp_path / "base"))
    base = np.asarray(mb.get_parameter("fc1", "kernel"))

    # victim: chaos delivers a REAL SIGTERM during step 4's update; the
    # in-flight step completes, the loop saves at the next boundary and
    # exits cleanly via Preempted (a SystemExit with code 0)
    monkeypatch.setenv("FF_CHAOS", "step:4=sigterm")
    m, dl = _build()
    with pytest.raises(Preempted) as ei:
        elastic_train(m, dl, epochs=2, checkpoint_dir=str(tmp_path / "ck"))
    assert ei.value.code == 0
    assert ei.value.step == 5
    meta = resilience.read_resume_meta(str(tmp_path / "ck"))
    assert meta["step"] == 5 and meta["steps_per_epoch"] == 3

    # "process restart": fresh model + loader, chaos off
    monkeypatch.delenv("FF_CHAOS")
    m2, dl2 = _build()
    elastic_train(m2, dl2, epochs=2, checkpoint_dir=str(tmp_path / "ck"))
    got = np.asarray(m2.get_parameter("fc1", "kernel"))
    assert m2._step_count == 6
    assert (got == base).all()  # bitwise — not just allclose


_CHILD = """
import os, sys
sys.path.insert(0, {root!r})
import numpy as np
import flexflow_tpu as ff
from flexflow_tpu.runtime.elastic import elastic_train

cfg = ff.FFConfig(batch_size=16)
m = ff.FFModel(cfg)
inp = m.create_tensor((16, 8), nchw=False, name="input")
t = m.dense(inp, 16, activation="relu", name="fc1")
t = m.dense(t, 4, name="fc2")
m.softmax(t, name="sm")
m.compile(ff.AdamOptimizer(alpha=0.01), "sparse_categorical_crossentropy",
          ["accuracy"])
m.init_layers(seed=9)
rng = np.random.default_rng(3)
x = rng.standard_normal((48, 8), dtype=np.float32)
y = rng.integers(0, 4, size=(48, 1), dtype=np.int32)
dl = ff.DataLoader(m, {{inp: x}}, y, seed=5)
print("READY", flush=True)
elastic_train(m, dl, epochs=40, checkpoint_dir={ckpt!r})
"""


def test_kill_term_subprocess_then_rerun_matches_uninterrupted(
        tmp_path, devices):
    """A real ``kill -TERM`` against a separate process mid-training:
    the child saves and exits 0; the rerun lands on the uninterrupted
    run's trajectory exactly (same global step => same params)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckpt = str(tmp_path / "ck")
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    for k in ("FF_CHAOS", "FF_TELEMETRY", "FF_HEALTH"):
        env.pop(k, None)
    code = _CHILD.format(root=root, ckpt=ckpt)
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE, text=True)
    assert proc.stdout.readline().strip() == "READY"
    # mid-epoch: give it time to get a few steps in, then kill
    import time
    time.sleep(3.0)
    proc.send_signal(signal.SIGTERM)
    assert proc.wait(timeout=120) == 0  # clean exit after the save

    meta = resilience.read_resume_meta(ckpt)
    assert meta is not None and meta["step"] > 0
    saved_step = int(meta["step"])

    # rerun up to a fixed target past the kill point, vs uninterrupted
    target_epochs = saved_step // 3 + 2
    m2, dl2 = _build()
    elastic_train(m2, dl2, epochs=target_epochs, checkpoint_dir=ckpt)
    mb, dlb = _build()
    elastic_train(mb, dlb, epochs=target_epochs,
                  checkpoint_dir=str(tmp_path / "base"))
    assert m2._step_count == mb._step_count
    got = np.asarray(m2.get_parameter("fc1", "kernel"))
    base = np.asarray(mb.get_parameter("fc1", "kernel"))
    assert (got == base).all()


# ---------------------------------------------------------------------------
# data / sync sites
# ---------------------------------------------------------------------------

def test_data_and_sync_sites_fire(monkeypatch, devices):
    monkeypatch.setenv("FF_CHAOS", "data:2=error;sync:1=error")
    m, dl = _build()
    dl.next_batch(m)          # data call 1: no fire
    with pytest.raises(ChaosError, match="data:2"):
        dl.next_batch(m)      # data call 2 fires
    with pytest.raises(ChaosError, match="sync:1"):
        m.sync()
