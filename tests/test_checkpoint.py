"""Checkpoint/resume + profiling hooks.

Beyond-reference subsystem (the reference persists only strategy files,
SURVEY §5.4): full train-state round-trip through orbax and npz, resume
continuity, and the per-op profile hook.
"""

import numpy as np
import pytest

import flexflow_tpu as ff


def _small_model(batch=16):
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="float32")
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 8), nchw=False)
    t = m.dense(inp, 16, activation="relu", name="fc1")
    t = m.dense(t, 4, name="fc2")
    m.softmax(t)
    m.compile(ff.SGDOptimizer(lr=0.1, momentum=0.9),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers(seed=3)
    return m, inp


def _feed(m, inp, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((16, 8), dtype=np.float32)
    y = rng.integers(0, 4, size=(16, 1), dtype=np.int32)
    m.set_batch({inp: x}, y)


def test_orbax_roundtrip_resume(devices, tmp_path):
    m, inp = _small_model()
    _feed(m, inp)
    for _ in range(3):
        m.train_iteration()
    m.sync()
    ckpt = str(tmp_path / "ckpt")
    m.save(ckpt)
    w_saved = m.get_parameter("fc1")
    step_saved = m._step_count

    # Diverge, then restore.
    for _ in range(2):
        m.train_iteration()
    m.sync()
    assert not np.allclose(m.get_parameter("fc1"), w_saved)
    m.load(ckpt)
    np.testing.assert_allclose(m.get_parameter("fc1"), w_saved)
    assert m._step_count == step_saved

    # Restored optimizer momentum: one more step must match a fresh model
    # restored to the same point taking the same step.
    _feed(m, inp, seed=1)
    m.train_iteration()
    m.sync()
    ref = m.get_parameter("fc1")

    m2, inp2 = _small_model()
    _feed(m2, inp2, seed=9)
    m2.train_iteration()  # builds opt state
    m2.sync()
    m2.load(ckpt)
    _feed(m2, inp2, seed=1)
    m2.train_iteration()
    m2.sync()
    np.testing.assert_allclose(m2.get_parameter("fc1"), ref, atol=1e-6)


def test_npz_roundtrip(devices, tmp_path):
    m, inp = _small_model()
    _feed(m, inp)
    m.train_iteration()
    m.sync()
    path = str(tmp_path / "weights.npz")
    m.save(path)
    w = m.get_parameter("fc2")
    for _ in range(2):
        m.train_iteration()
    m.sync()
    m.load(path)
    np.testing.assert_allclose(m.get_parameter("fc2"), w)


def test_checkpoint_manager_rotation(devices, tmp_path):
    from flexflow_tpu.runtime.checkpoint import CheckpointManager

    m, inp = _small_model()
    _feed(m, inp)
    mgr = CheckpointManager(str(tmp_path / "mgr"), max_to_keep=2)
    for _ in range(4):
        m.train_iteration()
        m.sync()
        mgr.save(m)
    mgr.wait_until_finished()
    step = m._step_count
    m.train_iteration()
    m.sync()
    restored = mgr.restore_latest(m)
    assert restored == step
    assert m._step_count == step
    mgr.close()


def test_op_profile_reports_all_ops(devices):
    m, inp = _small_model()
    prof = __import__("flexflow_tpu.runtime.profiling",
                      fromlist=["op_profile"]).op_profile(m, which="forward")
    assert set(prof) == {op.name for op in m.ops}
    assert all(v["forward_ms"] >= 0 for v in prof.values())
