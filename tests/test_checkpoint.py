"""Checkpoint/resume + profiling hooks.

Beyond-reference subsystem (the reference persists only strategy files,
SURVEY §5.4): full train-state round-trip through orbax and npz, resume
continuity, and the per-op profile hook.
"""

import numpy as np
import pytest

import flexflow_tpu as ff


def _small_model(batch=16):
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="float32")
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 8), nchw=False)
    t = m.dense(inp, 16, activation="relu", name="fc1")
    t = m.dense(t, 4, name="fc2")
    m.softmax(t)
    m.compile(ff.SGDOptimizer(lr=0.1, momentum=0.9),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers(seed=3)
    return m, inp


def _feed(m, inp, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((16, 8), dtype=np.float32)
    y = rng.integers(0, 4, size=(16, 1), dtype=np.int32)
    m.set_batch({inp: x}, y)


def test_orbax_roundtrip_resume(devices, tmp_path):
    m, inp = _small_model()
    _feed(m, inp)
    for _ in range(3):
        m.train_iteration()
    m.sync()
    ckpt = str(tmp_path / "ckpt")
    m.save(ckpt)
    w_saved = m.get_parameter("fc1")
    step_saved = m._step_count

    # Diverge, then restore.
    for _ in range(2):
        m.train_iteration()
    m.sync()
    assert not np.allclose(m.get_parameter("fc1"), w_saved)
    m.load(ckpt)
    np.testing.assert_allclose(m.get_parameter("fc1"), w_saved)
    assert m._step_count == step_saved

    # Restored optimizer momentum: one more step must match a fresh model
    # restored to the same point taking the same step.
    _feed(m, inp, seed=1)
    m.train_iteration()
    m.sync()
    ref = m.get_parameter("fc1")

    m2, inp2 = _small_model()
    _feed(m2, inp2, seed=9)
    m2.train_iteration()  # builds opt state
    m2.sync()
    m2.load(ckpt)
    _feed(m2, inp2, seed=1)
    m2.train_iteration()
    m2.sync()
    np.testing.assert_allclose(m2.get_parameter("fc1"), ref, atol=1e-6)


def test_npz_roundtrip(devices, tmp_path):
    m, inp = _small_model()
    _feed(m, inp)
    m.train_iteration()
    m.sync()
    path = str(tmp_path / "weights.npz")
    m.save(path)
    w = m.get_parameter("fc2")
    for _ in range(2):
        m.train_iteration()
    m.sync()
    m.load(path)
    np.testing.assert_allclose(m.get_parameter("fc2"), w)


def test_checkpoint_manager_rotation(devices, tmp_path):
    from flexflow_tpu.runtime.checkpoint import CheckpointManager

    m, inp = _small_model()
    _feed(m, inp)
    mgr = CheckpointManager(str(tmp_path / "mgr"), max_to_keep=2)
    for _ in range(4):
        m.train_iteration()
        m.sync()
        mgr.save(m)
    mgr.wait_until_finished()
    step = m._step_count
    m.train_iteration()
    m.sync()
    restored = mgr.restore_latest(m)
    assert restored == step
    assert m._step_count == step
    mgr.close()


def test_op_profile_reports_all_ops(devices):
    m, inp = _small_model()
    prof = __import__("flexflow_tpu.runtime.profiling",
                      fromlist=["op_profile"]).op_profile(m, which="forward")
    assert set(prof) == {op.name for op in m.ops}
    assert all(v["forward_ms"] >= 0 for v in prof.values())


def test_pipeline_checkpoint_layout_portable(devices, tmp_path):
    """Checkpoints canonicalize the packed pipeline stage-weight buffer
    to per-op arrays, so a save from a pipelined model restores into a
    plain model and vice versa (elastic resume across layout changes)."""
    import flexflow_tpu as ff

    def build(pipeline):
        cfg = ff.FFConfig(batch_size=16)
        m = ff.FFModel(cfg)
        inp = m.create_tensor((16, 16), nchw=False, name="x")
        t = m.dense(inp, 32, activation="relu", name="fc1")
        t = m.dense(t, 24, activation="relu", name="fc2")
        t = m.dense(t, 10, name="fc3")
        m.softmax(t, name="sm")
        if pipeline:
            m.set_pipeline(num_stages=2, num_microbatches=4, dp_degree=2)
        m.compile(ff.SGDOptimizer(lr=0.05, momentum=0.9),
                  "sparse_categorical_crossentropy", ["accuracy"])
        m.init_layers(seed=3)
        return m, inp

    m, inp = build(True)
    if m._pipeline_plan is None:
        pytest.skip("pipeline not expressible on this mesh")
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 16), dtype=np.float32)
    y = rng.integers(0, 10, size=(16, 1), dtype=np.int32)
    m.set_batch({inp: x}, y)
    m.train_iteration()
    m.sync()
    k1 = m.get_parameter("fc2", "kernel")
    p = str(tmp_path / "ckpt")
    m.save(p)

    # pipelined -> pipelined (packed buffer round-trips), resume trains
    m2, inp2 = build(True)
    m2.load(p)
    np.testing.assert_allclose(k1, m2.get_parameter("fc2", "kernel"),
                               rtol=1e-6)
    m2.set_batch({inp2: x}, y)
    m2.train_iteration()
    m2.sync()

    # pipelined -> plain (canonical per-op layout restores anywhere)
    m3, inp3 = build(False)
    m3.load(p)
    np.testing.assert_allclose(k1, m3.get_parameter("fc2", "kernel"),
                               rtol=1e-6)
    m3.set_batch({inp3: x}, y)
    m3.train_iteration()
    m3.sync()

    # plain -> pipelined (per-op arrays repack into the stage buffer)
    p2 = str(tmp_path / "ckpt2")
    m3.save(p2)
    m4, inp4 = build(True)
    m4.load(p2)
    np.testing.assert_allclose(m3.get_parameter("fc1", "kernel"),
                               m4.get_parameter("fc1", "kernel"), rtol=1e-6)
    m4.set_batch({inp4: x}, y)
    m4.train_iteration()
    m4.sync()
