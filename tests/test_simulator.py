"""Simulator + MCMC search tests.

Golden-property tests (SURVEY.md §4 implication: "golden-file tests for
the strategy search"): the simulator must rank obviously-better strategies
ahead of worse ones, and the search must return legal strategies that
simulate no slower than pure data parallelism.
"""

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.config import ParallelConfig
from flexflow_tpu.simulator.cost_model import CostModel
from flexflow_tpu.simulator.machine import TPUMachineModel
from flexflow_tpu.simulator.search import mcmc_search, random_parallel_config
from flexflow_tpu.simulator.simulator import Simulator


def tiny_model(batch=64):
    m = ff.FFModel(ff.FFConfig(batch_size=batch))
    inp = m.create_tensor((batch, 3, 32, 32))
    t = m.conv2d(inp, 16, 3, 3, 1, 1, 1, 1, activation="relu", name="conv1")
    t = m.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")
    t = m.flat(t, name="flat1")
    t = m.dense(t, 256, activation="relu", name="fc1")
    t = m.dense(t, 16, name="fc2")
    t = m.softmax(t, name="softmax1")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy", ["accuracy"])
    return m


def compute_heavy_model(batch=256):
    """Enough conv FLOPs per sample that DP beats single-device despite
    the gradient allreduce (the crossover the simulator must capture)."""
    m = ff.FFModel(ff.FFConfig(batch_size=batch))
    inp = m.create_tensor((batch, 3, 64, 64))
    t = m.conv2d(inp, 32, 3, 3, 1, 1, 1, 1, activation="relu", name="conv1")
    t = m.conv2d(t, 64, 3, 3, 1, 1, 1, 1, activation="relu", name="conv2")
    t = m.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")
    t = m.conv2d(t, 64, 3, 3, 1, 1, 1, 1, activation="relu", name="conv3")
    t = m.pool2d(t, 4, 4, 4, 4, 0, 0, name="pool2")
    t = m.flat(t, name="flat1")
    t = m.dense(t, 64, activation="relu", name="fc1")
    t = m.dense(t, 16, name="fc2")
    t = m.softmax(t, name="softmax1")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy", ["accuracy"])
    return m


def test_machine_model_torus():
    mm = TPUMachineModel(num_devices=16)
    assert mm.torus == (4, 4)
    assert mm.hops(0, 0) == 0
    assert mm.hops(0, 1) == 1
    # wraparound: chip 0 (0,0) to chip 3 (3,0) is 1 hop on a 4-ring
    assert mm.hops(0, 3) == 1
    assert mm.transfer_time(0, 0, 1e6) == 0.0
    assert mm.transfer_time(0, 1, 1e6) > 0.0
    # allreduce cost grows with bytes, sublinearly with group size
    t2 = mm.allreduce_time([0, 1], 1e6)
    t4 = mm.allreduce_time([0, 1, 2, 3], 1e6)
    assert t4 > t2
    assert t4 < 2 * t2


def test_simulator_prefers_parallelism(devices):
    m = compute_heavy_model()
    mm = TPUMachineModel(num_devices=8)
    sim = Simulator(mm, CostModel(mm, measure=False))
    single = {op.name: ParallelConfig(dims=(1,) * op.output.num_dims, device_ids=(0,))
              for op in m.ops}
    dp8 = {op.name: ParallelConfig.data_parallel(op.output.num_dims, 8)
           for op in m.ops}
    t1 = sim.simulate_runtime(m, single)
    t8 = sim.simulate_runtime(m, dp8)
    assert t8 < t1, f"DP8 ({t8}) should beat single-device ({t1})"


def test_simulator_charges_comm(devices):
    m = tiny_model()
    mm = TPUMachineModel(num_devices=8)
    sim = Simulator(mm, CostModel(mm, measure=False))
    dp = {op.name: ParallelConfig.data_parallel(op.output.num_dims, 8)
          for op in m.ops}
    # same strategy but fc1 split over channels: adds resharding comm
    mixed = dict(dp)
    mixed["fc1"] = ParallelConfig(dims=(1, 8), device_ids=tuple(range(8)))
    t_dp = sim.simulate_runtime(m, dp)
    t_mixed = sim.simulate_runtime(m, mixed)
    assert t_mixed != t_dp  # the comm model must see the difference


def test_random_config_is_legal(devices):
    import random

    m = tiny_model()
    rng = random.Random(0)
    for op in m.ops:
        for _ in range(20):
            pc = random_parallel_config(op, 8, rng)
            assert pc.num_parts() <= 8
            for i, d in enumerate(pc.dims):
                assert op.output.dims[i] % d == 0


def test_mcmc_search_improves_or_matches_dp(devices):
    m = tiny_model()
    best = mcmc_search(m, budget=60, alpha=0.05, seed=3, verbose=False)
    assert set(best) == {op.name for op in m.ops}
    mm = TPUMachineModel(num_devices=8)
    sim = Simulator(mm, CostModel(mm, measure=False))
    dp = {op.name: ParallelConfig.data_parallel(op.output.num_dims, 8)
          for op in m.ops}
    assert sim.simulate_runtime(m, best) <= sim.simulate_runtime(m, dp) * 1.0001


def test_search_result_trains(devices):
    """The searched strategy must actually run: compile a model with it."""
    m = tiny_model(batch=32)
    best = mcmc_search(m, budget=30, alpha=0.05, seed=1, verbose=False)
    cfg = ff.FFConfig(batch_size=32, strategies=best)
    m2 = ff.FFModel(cfg)
    inp = m2.create_tensor((32, 3, 32, 32))
    t = m2.conv2d(inp, 16, 3, 3, 1, 1, 1, 1, activation="relu", name="conv1")
    t = m2.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")
    t = m2.flat(t, name="flat1")
    t = m2.dense(t, 256, activation="relu", name="fc1")
    t = m2.dense(t, 16, name="fc2")
    t = m2.softmax(t, name="softmax1")
    m2.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy", ["accuracy"])
    m2.init_layers()
    dl = ff.DataLoader.synthetic(m2, inp, num_samples=32, num_classes=16)
    dl.next_batch(m2)
    m2.train_iteration()
    m2.sync()


def test_compile_runs_search_with_budget(devices, tmp_path):
    path = str(tmp_path / "searched.pb")
    cfg = ff.FFConfig(batch_size=64, search_budget=20,
                      export_strategy_file=path)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((64, 3, 16, 16))
    t = m.conv2d(inp, 8, 3, 3, 1, 1, 1, 1, name="c1")
    t = m.flat(t, name="f1")
    t = m.dense(t, 32, name="d1")
    t = m.softmax(t, name="s1")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy", ["accuracy"])
    loaded = ff.load_strategies_from_file(path)
    assert set(loaded) == {"c1", "f1", "d1", "s1"}


def test_host_embedding_cost_scales_with_batch_not_table(devices):
    """Host-placed (row-sparse) embedding pricing mirrors the runtime:
    per-step cost follows the BATCH's rows, independent of table size
    (reference: embedding.cc CPU tasks touch only the batch's rows)."""
    from flexflow_tpu.config import DeviceType, ParallelConfig
    from flexflow_tpu.simulator.cost_model import CostModel
    from flexflow_tpu.simulator.machine import TPUMachineModel

    def emb_op(batch, rows):
        m = ff.FFModel(ff.FFConfig(batch_size=batch))
        ids = m.create_tensor((batch, 2), dtype="int32", name="ids")
        m.embedding(ids, rows, 16, name="emb")
        return m.ops[0]

    mm = TPUMachineModel(num_devices=8)
    cost = CostModel(mm, measure=False)
    cpu_pc = ParallelConfig(DeviceType.CPU, (1, 1), (0,),
                            ("host", "host", "host"))
    t_small = cost.op_time(emb_op(64, 10_000), cpu_pc, "forward")
    t_large = cost.op_time(emb_op(64, 10_000_000), cpu_pc, "forward")
    assert t_small == t_large  # table size is NOT in the cost
    t_2x = cost.op_time(emb_op(128, 10_000), cpu_pc, "forward")
    assert t_2x > t_small  # batch rows ARE
    # backward adds the PCIe return + host scatter
    t_bwd = cost.op_time(emb_op(64, 10_000), cpu_pc, "backward")
    assert t_bwd > t_small


def test_host_embedding_prices_transfer_latency(devices):
    """The fitted per-transfer host<->device latency (tens of ms behind
    the tunnel) must raise the host-embedding cost — without it the
    search over-recommends host placement on latency-bound deployments."""
    import flexflow_tpu as ff
    from flexflow_tpu.simulator.cost_model import CostModel
    from flexflow_tpu.simulator.machine import TPUMachineModel

    cfg = ff.FFConfig(batch_size=64)
    m = ff.FFModel(cfg)
    ids = m.create_tensor((64, 4), dtype="int32", name="ids")
    m.embedding(ids, 10000, 16, name="emb")
    op = m.ops[0]
    pc = ff.ParallelConfig.host_rowsparse()
    base = CostModel(TPUMachineModel(num_devices=8),
                     measure=False).op_time(op, pc, "forward")
    slow = CostModel(TPUMachineModel(num_devices=8, host_xfer_latency=30e-3),
                     measure=False).op_time(op, pc, "forward")
    assert slow > base + 25e-3
