"""Fused Pallas optimizer kernels vs the jnp update path.

The reference hand-writes its update kernels (optimizer_kernel.cu:23-40
sgd_update, :206-225 adam_update); kernels/fused_optimizer.py is the
Pallas analogue.  These tests pin the kernels (interpret mode on CPU)
against the jnp formulas, per-leaf and end-to-end through FFModel with
``FFConfig.fused_optimizer=True`` on a single-device machine.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import flexflow_tpu as ff
from flexflow_tpu.kernels.fused_optimizer import (fused_adam_update,
                                                  fused_sgd_update)
from flexflow_tpu.optimizers import AdamOptimizer, SGDOptimizer


@pytest.mark.parametrize("momentum,nesterov", [(0.0, False), (0.9, False),
                                               (0.9, True)])
@pytest.mark.parametrize("shape", [(7,), (33, 5), (4, 3, 9)])
def test_fused_sgd_matches_jnp(shape, momentum, nesterov):
    rng = np.random.default_rng(0)
    w = rng.standard_normal(shape).astype(np.float32)
    g = rng.standard_normal(shape).astype(np.float32)
    v = rng.standard_normal(shape).astype(np.float32)
    lr, wd = 0.05, 1e-4

    w2, v2 = fused_sgd_update(jnp.asarray(w), jnp.asarray(g), jnp.asarray(v),
                              lr, wd, momentum, nesterov)
    # jnp reference (optimizers.py formulas)
    gt = g + wd * w
    if momentum > 0.0:
        vr = momentum * v + gt
        step = gt + momentum * vr if nesterov else vr
    else:
        vr = v
        step = gt
    wr = w - lr * step
    np.testing.assert_allclose(np.asarray(w2), wr, rtol=1e-6, atol=1e-6)
    if momentum > 0.0:
        np.testing.assert_allclose(np.asarray(v2), vr, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("shape", [(129,), (16, 40)])
def test_fused_adam_matches_jnp(shape):
    rng = np.random.default_rng(1)
    w = rng.standard_normal(shape).astype(np.float32)
    g = rng.standard_normal(shape).astype(np.float32)
    m = rng.standard_normal(shape).astype(np.float32)
    v = np.abs(rng.standard_normal(shape)).astype(np.float32)
    alpha_t, wd, b1, b2, eps = 0.01, 1e-4, 0.9, 0.999, 1e-8

    w2, m2, v2 = fused_adam_update(jnp.asarray(w), jnp.asarray(g),
                                   jnp.asarray(m), jnp.asarray(v),
                                   alpha_t, wd, b1, b2, eps)
    gt = g + wd * w
    mr = b1 * m + (1 - b1) * gt
    vr = b2 * v + (1 - b2) * gt * gt
    wr = w - alpha_t * mr / (np.sqrt(vr) + eps)
    np.testing.assert_allclose(np.asarray(w2), wr, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m2), mr, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v2), vr, rtol=1e-6, atol=1e-6)


def _train(fused, opt_name, steps=4):
    cfg = ff.FFConfig(batch_size=8, fused_optimizer=fused)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((8, 12), nchw=False)
    t = m.dense(inp, 16, activation=ff.ActiMode.RELU, name="fc1")
    t = m.dense(t, 6, name="fc2")
    m.softmax(t, name="sm")
    opt = (SGDOptimizer(lr=0.05, momentum=0.9) if opt_name == "sgd"
           else AdamOptimizer(alpha=0.01))
    from flexflow_tpu.parallel.mesh import Machine
    m.compile(opt, "sparse_categorical_crossentropy", ["accuracy"],
              machine=Machine(devices=jax.devices()[:1]))
    assert opt.fused == fused
    m.init_layers(seed=4)
    rng = np.random.default_rng(2)
    x = rng.standard_normal((8, 12), dtype=np.float32)
    y = rng.integers(0, 6, size=(8, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()
    m.sync()
    return m.get_parameter("fc1", "kernel"), m.get_parameter("fc2", "kernel")


@pytest.mark.parametrize("opt_name", ["sgd", "adam"])
def test_fused_end_to_end_parity(opt_name):
    a_ref, b_ref = _train(False, opt_name)
    a_f, b_f = _train(True, opt_name)
    np.testing.assert_allclose(a_ref, a_f, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(b_ref, b_f, rtol=1e-5, atol=1e-6)


def _train_mesh(fused, opt_name, steps=3):
    """Train on the full 8-device mesh with a TP dense: the fused path
    must run per-shard (per-leaf shard_map with the param's own spec)."""
    strategies = {
        "fc1": ff.ParallelConfig(dims=(2, 4)),   # tensor parallel
        "fc2": ff.ParallelConfig(dims=(8, 1)),
        "sm": ff.ParallelConfig(dims=(8, 1)),
    }
    cfg = ff.FFConfig(batch_size=8, fused_optimizer=fused,
                      strategies=strategies)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((8, 12), nchw=False)
    t = m.dense(inp, 16, activation=ff.ActiMode.RELU, name="fc1")
    t = m.dense(t, 6, name="fc2")
    m.softmax(t, name="sm")
    opt = (SGDOptimizer(lr=0.05, momentum=0.9) if opt_name == "sgd"
           else AdamOptimizer(alpha=0.01))
    m.compile(opt, "sparse_categorical_crossentropy", ["accuracy"])
    assert opt.fused == fused
    m.init_layers(seed=4)
    if fused:
        # TP kernel actually sharded + specs installed on the optimizer
        assert opt.mesh is not None
        spec = m._params["fc1"]["kernel"].sharding.spec
        assert len(spec) >= 2 and spec[1] is not None
    rng = np.random.default_rng(2)
    x = rng.standard_normal((8, 12), dtype=np.float32)
    y = rng.integers(0, 6, size=(8, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()
    m.sync()
    return m.get_parameter("fc1", "kernel"), m.get_parameter("fc2", "kernel")


@pytest.mark.parametrize("opt_name", ["sgd", "adam"])
def test_fused_sharded_mesh_parity(devices, opt_name):
    """Fused per-shard updates on the 8-device mesh == plain updates
    (VERDICT r2 weak #4: the fused path must work under sharding)."""
    a_ref, b_ref = _train_mesh(False, opt_name)
    a_f, b_f = _train_mesh(True, opt_name)
    np.testing.assert_allclose(a_ref, a_f, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(b_ref, b_f, rtol=1e-5, atol=1e-6)
