"""Chipwatch tests: subprocess probes (never hang the parent), capped
backoff, and the window-conversion invariant the whole layer exists
for — a KILLED measurement subprocess still leaves a readable,
monotonically grown measured cache (docs/observability.md "Chip-session
perf observatory")."""

import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, ".")

from flexflow_tpu.observability import chipwatch  # noqa: E402
from flexflow_tpu.observability import events  # noqa: E402

# Fake probe commands: plain python -c, no jax import — fast.
PROBE_OK = [sys.executable, "-c", "print('TPU_OK fake_v5e 1.0')"]
PROBE_FAIL = [sys.executable, "-c",
              "import sys; print('no tpu', file=sys.stderr); sys.exit(1)"]
PROBE_HANG = [sys.executable, "-c", "import time; time.sleep(600)"]

# Fake measurement backend: grows a measured-cache file one entry at a
# time (atomic tmp+rename, like CostModel._persist), resuming from
# whatever a previous interrupted window already persisted.
FAKE_MEASURE = r"""
import json, os, sys, time
path, n, delay = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
data = {}
if os.path.exists(path):
    data = json.load(open(path))
start = len(data)
for i in range(start, start + n):
    data[f"FakeOp:({i},):():k:bfloat16:forward"] = {
        "t": 1e-4, "measured": True, "platform": "tpu"}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f)
    os.replace(tmp, path)
    time.sleep(delay)
"""


def _measure_cmd(cache, n, delay):
    return [sys.executable, "-c", FAKE_MEASURE, cache, str(n), str(delay)]


def test_probe_once_ok():
    res = chipwatch.probe_once(timeout=30.0, probe_cmd=PROBE_OK)
    assert res.ok and res.device_kind == "fake_v5e"
    assert res.latency_s >= 0


def test_probe_once_failure_carries_stderr():
    res = chipwatch.probe_once(timeout=30.0, probe_cmd=PROBE_FAIL)
    assert not res.ok
    assert "no tpu" in res.detail


def test_probe_once_kills_wedged_child():
    t0 = time.monotonic()
    res = chipwatch.probe_once(timeout=1.0, probe_cmd=PROBE_HANG)
    assert not res.ok
    assert "wedged" in res.detail
    # the parent must come back promptly — the child was killed, the
    # 600s sleep never ran to completion
    assert time.monotonic() - t0 < 30.0


def test_backoff_is_capped():
    delays = chipwatch.backoff_delays(initial=10.0, factor=2.0, cap=35.0)
    got = [next(delays) for _ in range(5)]
    assert got == [10.0, 20.0, 35.0, 35.0, 35.0]


def test_wait_for_chip_backs_off_then_gives_up():
    slept = []
    res = chipwatch.wait_for_chip(budget_s=3600.0, probe_timeout=30.0,
                                  probe_cmd=PROBE_FAIL,
                                  initial_backoff=0.25, backoff_factor=2.0,
                                  backoff_cap=0.6, max_probes=4,
                                  sleep=slept.append)
    assert res is None
    assert slept == [0.25, 0.5, 0.6]  # no sleep after the final probe


def test_wait_for_chip_returns_first_success():
    slept = []
    res = chipwatch.wait_for_chip(budget_s=3600.0, probe_timeout=30.0,
                                  probe_cmd=PROBE_OK, max_probes=5,
                                  sleep=slept.append)
    assert res is not None and res.ok
    assert slept == []


def test_wait_for_chip_respects_budget():
    # budget smaller than the first backoff -> exactly one probe
    slept = []
    res = chipwatch.wait_for_chip(budget_s=0.1, probe_timeout=30.0,
                                  probe_cmd=PROBE_FAIL,
                                  initial_backoff=5.0, sleep=slept.append)
    assert res is None and slept == []


def test_read_measured_count_filters_platform(tmp_path):
    p = tmp_path / "cache.json"
    p.write_text(json.dumps({
        "a": {"t": 1e-3, "measured": True, "platform": "tpu"},
        "b": {"t": 1e-3, "measured": True, "platform": "cpu"},
        "c": {"t": 1e-3, "measured": False, "platform": "tpu"},
        "d": "legacy-bare-float"}))
    assert chipwatch.read_measured_count(str(p), "tpu") == 1
    assert chipwatch.read_measured_count(str(tmp_path / "missing.json")) == 0
    p.write_text('{"torn mid-wri')
    assert chipwatch.read_measured_count(str(p)) is None


def test_convert_window_completes_and_counts(tmp_path):
    cache = str(tmp_path / "measured.json")
    win = chipwatch.convert_window(
        cache_path=cache, measure_cmd=_measure_cmd(cache, 5, 0.01),
        max_seconds=30.0, poll_every=0.05, refit=False)
    assert win.converted
    assert win.entries_before == 0 and win.entries_after == 5
    assert win.measure_rc == 0
    assert win.refit_rc is None  # refit=False
    json.load(open(cache))  # cache is valid JSON


def test_interrupted_window_grows_cache_monotonically(tmp_path):
    """The acceptance-criteria test: a chipwatch window whose
    measurement subprocess is KILLED mid-run (budget exhausted — the
    wedged-tunnel stand-in) still leaves a readable cache, and a second
    interrupted window resumes and grows it MONOTONICALLY."""
    cache = str(tmp_path / "measured.json")
    # the fake backend wants 500 entries at 50ms each (~25s); the
    # window budget kills it after ~0.5s
    win1 = chipwatch.convert_window(
        cache_path=cache, measure_cmd=_measure_cmd(cache, 500, 0.05),
        max_seconds=0.5, grace=0.0, poll_every=0.05, refit=False)
    assert win1.converted, win1
    assert win1.measure_rc != 0  # it really was killed
    n1 = chipwatch.read_measured_count(cache)
    assert n1 == win1.entries_after
    assert 0 < n1 < 500
    json.load(open(cache))  # no partial JSON despite the kill
    # second window: resumes from the durable cache, grows it further
    win2 = chipwatch.convert_window(
        cache_path=cache, measure_cmd=_measure_cmd(cache, 500, 0.05),
        max_seconds=0.5, grace=0.0, poll_every=0.05, refit=False)
    assert win2.entries_before == n1
    assert win2.entries_after > win2.entries_before
    assert chipwatch.read_measured_count(cache) >= n1


def test_convert_window_stall_kill(tmp_path):
    cache = str(tmp_path / "measured.json")
    # one entry, then the "backend" hangs without producing more
    hang = [sys.executable, "-c", FAKE_MEASURE.replace(
        "time.sleep(delay)", "time.sleep(delay if i > start else 600)"),
        cache, "5", "0.01"]
    win = chipwatch.convert_window(
        cache_path=cache, measure_cmd=hang, max_seconds=60.0,
        poll_every=0.05, stall_timeout=0.5, refit=False)
    assert win.converted and win.entries_after == 1
    assert "no cache growth" in win.detail


def test_window_emits_telemetry_events(tmp_path, monkeypatch):
    trace = tmp_path / "trace.jsonl"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    events.reset_active()
    try:
        cache = str(tmp_path / "measured.json")
        chipwatch.probe_once(timeout=30.0, probe_cmd=PROBE_FAIL)
        chipwatch.convert_window(
            cache_path=cache, measure_cmd=_measure_cmd(cache, 3, 0.01),
            max_seconds=30.0, poll_every=0.05, refit=False)
    finally:
        events.reset_active()
    names = [json.loads(l)["name"] for l in trace.read_text().splitlines()
             if '"name"' in l]
    assert "chip_probe" in names
    assert "measurement_progress" in names
    assert "chip_window" in names
    # and trace_report folds them into a Measurement section
    from flexflow_tpu.tools import trace_report

    rep = trace_report.render_report(trace_report.parse_trace(str(trace)))
    assert "## Measurement" in rep
    assert "window converted" in rep


def test_cost_model_persist_survives_sigkill(tmp_path):
    """CostModel._persist is atomic tmp+rename: SIGKILL a process that
    persists in a tight loop, the cache must still parse."""
    cache = str(tmp_path / "simcache.json")
    code = (
        "import sys\n"
        "sys.path.insert(0, %r)\n"
        "from flexflow_tpu.simulator.cost_model import CostModel\n"
        "from flexflow_tpu.simulator.machine import TPUMachineModel\n"
        "cm = CostModel(TPUMachineModel(num_devices=1), cache_path=%r)\n"
        "print('READY', flush=True)\n"
        "i = 0\n"
        "while True:\n"
        "    cm._persist(f'Dense:({8},):():h{i}:bfloat16:forward', 1e-4)\n"
        "    i += 1\n" % (os.getcwd(), cache))
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().startswith("READY")
        deadline = time.monotonic() + 20.0
        while not os.path.exists(cache) and time.monotonic() < deadline:
            time.sleep(0.02)
        time.sleep(0.3)  # let many read-modify-write cycles run
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    data = json.load(open(cache))  # would raise on a torn write
    assert len(data) >= 1
    assert all(v.get("measured") for v in data.values())


def test_chipwatch_probe_only_cli(tmp_path, capsys):
    # --probe-only against the real probe code would need a chip; the
    # CLI is exercised through probe_once's injectable path elsewhere —
    # here just check the module entrypoint parses args and reports a
    # failed probe as rc 1 (PROBE_CODE asserts platform=='tpu', and the
    # test suite pins cpu).
    rc = chipwatch.main(["--probe-only", "--probe-timeout", "60"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(out)
    assert rc == 1 and rec["ok"] is False
