"""Optimizer updates vs. the reference kernel formulas in numpy.

Reference: sgd_update (optimizer_kernel.cu:23-40), adam_update (:206-225)
and the alpha_t schedule (optimizer.cc AdamOptimizer::next_epoch).
"""

import numpy as np
import jax.numpy as jnp

from flexflow_tpu.optimizers import AdamOptimizer, SGDOptimizer


def np_sgd(w, g, v, lr, wd, mom, nesterov):
    gt = g + wd * w
    if mom > 0:
        v = v * mom + gt
        gt = gt + mom * v if nesterov else v
    return w - lr * gt, v


def test_sgd_plain_and_momentum_and_nesterov():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((5, 3), dtype=np.float32)
    g = rng.standard_normal((5, 3), dtype=np.float32)

    for mom, nest in [(0.0, False), (0.9, False), (0.9, True)]:
        opt = SGDOptimizer(lr=0.1, momentum=mom, nesterov=nest, weight_decay=1e-4)
        params = {"w": jnp.asarray(w)}
        state = opt.init_state(params)
        p1, s1 = opt.apply(params, {"w": jnp.asarray(g)}, state, opt.hparams())
        w_ref, v_ref = np_sgd(w, g, np.zeros_like(w), 0.1, 1e-4, mom, nest)
        np.testing.assert_allclose(np.asarray(p1["w"]), w_ref, rtol=1e-6, atol=1e-6)
        # second step exercises the momentum buffer
        g2 = rng.standard_normal((5, 3), dtype=np.float32)
        p2, s2 = opt.apply(p1, {"w": jnp.asarray(g2)}, s1, opt.hparams())
        w_ref2, v_ref2 = np_sgd(w_ref, g2, v_ref, 0.1, 1e-4, mom, nest)
        np.testing.assert_allclose(np.asarray(p2["w"]), w_ref2, rtol=1e-6, atol=1e-6)


def test_adam_matches_reference_formula():
    rng = np.random.default_rng(1)
    w = rng.standard_normal((7,), dtype=np.float32)
    opt = AdamOptimizer(alpha=1e-3, beta1=0.9, beta2=0.999, weight_decay=1e-4, epsilon=1e-8)
    params = {"w": jnp.asarray(w)}
    state = opt.init_state(params)

    m = np.zeros_like(w)
    v = np.zeros_like(w)
    w_ref = w.copy()
    for step in range(3):
        opt.next_epoch()  # reference advances schedule before updates
        g = rng.standard_normal((7,), dtype=np.float32)
        params, state = opt.apply(params, {"w": jnp.asarray(g)}, state, opt.hparams())
        b1t = 0.9 ** (step + 1)
        b2t = 0.999 ** (step + 1)
        alpha_t = 1e-3 * np.sqrt(1 - b2t) / (1 - b1t)
        gt = g + 1e-4 * w_ref
        m = 0.9 * m + 0.1 * gt
        v = 0.999 * v + 0.001 * gt * gt
        w_ref = w_ref - alpha_t * m / (np.sqrt(v) + 1e-8)
        np.testing.assert_allclose(np.asarray(params["w"]), w_ref, rtol=1e-5, atol=1e-6)
