"""Optimizer updates vs. the reference kernel formulas in numpy.

Reference: sgd_update (optimizer_kernel.cu:23-40), adam_update (:206-225)
and the alpha_t schedule (optimizer.cc AdamOptimizer::next_epoch).
"""

import numpy as np
import jax.numpy as jnp

import flexflow_tpu as ff
from flexflow_tpu.optimizers import AdamOptimizer, SGDOptimizer


def np_sgd(w, g, v, lr, wd, mom, nesterov):
    gt = g + wd * w
    if mom > 0:
        v = v * mom + gt
        gt = gt + mom * v if nesterov else v
    return w - lr * gt, v


def test_sgd_plain_and_momentum_and_nesterov():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((5, 3), dtype=np.float32)
    g = rng.standard_normal((5, 3), dtype=np.float32)

    for mom, nest in [(0.0, False), (0.9, False), (0.9, True)]:
        opt = SGDOptimizer(lr=0.1, momentum=mom, nesterov=nest, weight_decay=1e-4)
        params = {"w": jnp.asarray(w)}
        state = opt.init_state(params)
        p1, s1 = opt.apply(params, {"w": jnp.asarray(g)}, state, opt.hparams())
        w_ref, v_ref = np_sgd(w, g, np.zeros_like(w), 0.1, 1e-4, mom, nest)
        np.testing.assert_allclose(np.asarray(p1["w"]), w_ref, rtol=1e-6, atol=1e-6)
        # second step exercises the momentum buffer
        g2 = rng.standard_normal((5, 3), dtype=np.float32)
        p2, s2 = opt.apply(p1, {"w": jnp.asarray(g2)}, s1, opt.hparams())
        w_ref2, v_ref2 = np_sgd(w_ref, g2, v_ref, 0.1, 1e-4, mom, nest)
        np.testing.assert_allclose(np.asarray(p2["w"]), w_ref2, rtol=1e-6, atol=1e-6)


def test_adam_matches_reference_formula():
    rng = np.random.default_rng(1)
    w = rng.standard_normal((7,), dtype=np.float32)
    opt = AdamOptimizer(alpha=1e-3, beta1=0.9, beta2=0.999, weight_decay=1e-4, epsilon=1e-8)
    params = {"w": jnp.asarray(w)}
    state = opt.init_state(params)

    m = np.zeros_like(w)
    v = np.zeros_like(w)
    w_ref = w.copy()
    for step in range(3):
        opt.next_epoch()  # reference advances schedule before updates
        g = rng.standard_normal((7,), dtype=np.float32)
        params, state = opt.apply(params, {"w": jnp.asarray(g)}, state, opt.hparams())
        b1t = 0.9 ** (step + 1)
        b2t = 0.999 ** (step + 1)
        alpha_t = 1e-3 * np.sqrt(1 - b2t) / (1 - b1t)
        gt = g + 1e-4 * w_ref
        m = 0.9 * m + 0.1 * gt
        v = 0.999 * v + 0.001 * gt * gt
        w_ref = w_ref - alpha_t * m / (np.sqrt(v) + 1e-8)
        np.testing.assert_allclose(np.asarray(params["w"]), w_ref, rtol=1e-5, atol=1e-6)


def test_optax_adapter_matches_builtin_sgd(devices):
    """OptaxOptimizer(optax.sgd(lr)) == built-in SGDOptimizer over
    several steps (same update rule, state riding the fused step)."""
    import optax

    def run(opt):
        cfg = ff.FFConfig(batch_size=16)
        m = ff.FFModel(cfg)
        inp = m.create_tensor((16, 8), nchw=False)
        t = m.dense(inp, 16, activation="relu", name="fc1")
        t = m.dense(t, 4, name="fc2")
        m.softmax(t, name="sm")
        m.compile(opt, "sparse_categorical_crossentropy", ["accuracy"])
        m.init_layers(seed=4)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 8), dtype=np.float32)
        y = rng.integers(0, 4, size=(16, 1), dtype=np.int32)
        m.set_batch({inp: x}, y)
        for _ in range(4):
            m.train_iteration()
        m.sync()
        return m.get_parameter("fc1", "kernel"), m

    k_ref, _ = run(ff.SGDOptimizer(lr=0.1))
    k_opx, _ = run(ff.OptaxOptimizer(optax.sgd(0.1)))
    np.testing.assert_allclose(k_ref, k_opx, rtol=1e-5, atol=1e-6)


def test_optax_adamw_trains_and_checkpoints(devices, tmp_path):
    """An optax chain (clip + adamw) trains, and its NamedTuple state
    survives a save/load round-trip and keeps training."""
    import optax

    def build():
        cfg = ff.FFConfig(batch_size=16)
        m = ff.FFModel(cfg)
        inp = m.create_tensor((16, 8), nchw=False)
        t = m.dense(inp, 32, activation="relu", name="fc1")
        t = m.dense(t, 4, name="fc2")
        m.softmax(t, name="sm")
        m.compile(ff.OptaxOptimizer(
            optax.chain(optax.clip_by_global_norm(1.0),
                        optax.adamw(1e-2))),
            "sparse_categorical_crossentropy", ["accuracy"])
        m.init_layers(seed=4)
        return m, inp

    m, inp = build()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 8), dtype=np.float32)
    y = np.argmax(x[:, :4], 1).astype(np.int32)[:, None]
    losses = []
    for _ in range(15):
        m.set_batch({inp: x}, y)
        m.train_iteration()
        m.sync()
        m.get_metrics()
        losses.append(m.last_loss)
        m.reset_metrics()
    assert losses[-1] < losses[0] * 0.5, losses

    # npz path explicitly: pins the NamedTuple rebuild + mesh
    # re-placement of the non-dict optax state (the orbax path would
    # otherwise shadow it in CI)
    p = str(tmp_path / "ckpt.npz")
    m.save(p)
    m2, inp2 = build()
    m2.load(p)
    np.testing.assert_allclose(m.get_parameter("fc1", "kernel"),
                               m2.get_parameter("fc1", "kernel"), rtol=1e-6)
    m2.set_batch({inp2: x}, y)
    m2.train_iteration()
    m2.sync()

    p2 = str(tmp_path / "ckpt_orbax")
    m.save(p2)
    m3, inp3 = build()
    m3.load(p2)
    np.testing.assert_allclose(m.get_parameter("fc1", "kernel"),
                               m3.get_parameter("fc1", "kernel"), rtol=1e-6)
    m3.set_batch({inp3: x}, y)
    m3.train_iteration()
    m3.sync()


def test_optax_pipelined_checkpoint_portability(devices, tmp_path):
    """optax slot states nest params-shaped dicts inside NamedTuples;
    a pipelined model's packed '_pipe' buffer inside those nodes must
    canonicalize on save and repack on restore — including restoring
    into a PLAIN model (layout portability)."""
    import optax

    def build(pipeline):
        cfg = ff.FFConfig(batch_size=16)
        m = ff.FFModel(cfg)
        inp = m.create_tensor((16, 16), nchw=False, name="x")
        t = m.dense(inp, 32, activation="relu", name="fc1")
        t = m.dense(t, 24, activation="relu", name="fc2")
        t = m.dense(t, 4, name="fc3")
        m.softmax(t, name="sm")
        if pipeline:
            m.set_pipeline(num_stages=2, num_microbatches=4, dp_degree=2)
        m.compile(ff.OptaxOptimizer(optax.adamw(1e-2)),
                  "sparse_categorical_crossentropy", ["accuracy"])
        m.init_layers(seed=6)
        return m, inp

    m, inp = build(True)
    if m._pipe_pack() is None:
        import pytest
        pytest.skip("pipeline not expressible on this mesh")
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 16), dtype=np.float32)
    y = rng.integers(0, 4, size=(16, 1), dtype=np.int32)
    m.set_batch({inp: x}, y)
    m.train_iteration()
    m.sync()
    p = str(tmp_path / "ckpt.npz")
    m.save(p)

    # packed -> packed
    m2, inp2 = build(True)
    m2.load(p)
    np.testing.assert_allclose(m.get_parameter("fc2", "kernel"),
                               m2.get_parameter("fc2", "kernel"), rtol=1e-6)
    m2.set_batch({inp2: x}, y)
    m2.train_iteration()
    m2.sync()

    # packed -> plain (canonical slot layout restores anywhere)
    m3, inp3 = build(False)
    m3.load(p)
    np.testing.assert_allclose(m.get_parameter("fc2", "kernel"),
                               m3.get_parameter("fc2", "kernel"), rtol=1e-6)
    m3.set_batch({inp3: x}, y)
    m3.train_iteration()
    m3.sync()
