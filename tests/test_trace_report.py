"""trace_report CLI tests: percentile math, report sections on a
synthetic trace, corrupt-tail tolerance, and a byte-exact golden check
(the report is a committed artifact format — changes must be deliberate)."""

import json
import os
import sys

sys.path.insert(0, ".")

from flexflow_tpu.tools import trace_report

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "trace_report.md")


def synthetic_records():
    """Deterministic mini-trace exercising every report section."""
    recs = [{"t": "meta", "version": 1, "run_id": "golden-run", "pid": 4242,
             "unix_time": 1700000000.0}]
    recs.append({"t": "span", "name": "compile", "id": 1, "parent": None,
                 "ts": 0.1, "dur": 1.25,
                 "attrs": {"num_ops": 6, "num_devices": 8}})
    # step 0 carries the jit trace + compile; steps 1..4 steady-state
    durs = [2.0, 0.010, 0.012, 0.011, 0.020]
    ts = 2.0
    for i, d in enumerate(durs):
        recs.append({"t": "span", "name": "step", "id": 2 + i,
                     "parent": None, "ts": round(ts, 6), "dur": d,
                     "attrs": {"step": i, "first": i == 0, "batch_size": 64,
                               "samples_per_sec": round(64 / d, 2),
                               "samples_per_sec_per_chip":
                                   round(64 / d / 8, 2),
                               "mfu": round(0.002 / d, 6)}})
        recs.append({"t": "counter", "name": "samples", "v": 64.0,
                     "total": 64.0 * (i + 1), "ts": round(ts + d, 6)})
        recs.append({"t": "gauge", "name": "samples_per_sec",
                     "v": round(64 / d, 2), "ts": round(ts + d, 6)})
        recs.append({"t": "gauge", "name": "mfu", "v": round(0.002 / d, 6),
                     "ts": round(ts + d, 6)})
        recs.append({"t": "span", "name": "data_wait", "id": 100 + i,
                     "parent": None, "ts": round(ts - 0.001, 6),
                     "dur": 0.001, "attrs": {"batch_size": 64,
                                             "prefetched": i > 0}})
        ts += d + 0.002
    recs.append({"t": "gauge", "name": "first_step_wall_s", "v": 2.0,
                 "ts": 4.0})
    recs.append({"t": "gauge", "name": "est_collective_bytes_per_step",
                 "v": 1572864.0, "ts": 4.0})
    recs.append({"t": "span", "name": "metric_drain", "id": 50,
                 "parent": None, "ts": 8.0, "dur": 0.003, "attrs": {}})
    recs.append({"t": "span", "name": "checkpoint_save", "id": 51,
                 "parent": None, "ts": 9.0, "dur": 0.5,
                 "attrs": {"path": "/tmp/ckpt.npz", "step": 5}})
    for op, fwd, bwd in [("conv1", 1.5, 3.0), ("dense1", 0.4, 0.8),
                         ("pool1", 0.1, 0.1)]:
        recs.append({"t": "event", "name": "op_profile", "ts": 10.0,
                     "attrs": {"op": op, "forward_ms": fwd,
                               "backward_ms": bwd}})
    for i, phase in enumerate(["preflight", "compile", "warmup", "measure"]):
        recs.append({"t": "event", "name": "bench_phase",
                     "ts": float(i), "attrs": {"phase": phase}})
    for it, best in [(0, 9.5), (100, 7.2), (200, 6.8)]:
        recs.append({"t": "event", "name": "search_progress", "ts": 11.0,
                     "attrs": {"engine": "mcmc", "iter": it,
                               "best_ms": best}})
    recs.append({"t": "span", "name": "mcmc_search", "id": 60,
                 "parent": None, "ts": 11.0, "dur": 2.5,
                 "attrs": {"budget": 250, "best_ms": 6.8}})
    return recs


def write_trace(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_percentile():
    assert trace_report.percentile([], 50) == 0.0
    assert trace_report.percentile([3.0], 95) == 3.0
    assert trace_report.percentile([1.0, 2.0, 3.0, 4.0], 50) == 2.5
    assert trace_report.percentile([1.0, 2.0, 3.0, 4.0], 100) == 4.0


def test_report_sections(tmp_path):
    path = str(tmp_path / "t.jsonl")
    write_trace(path, synthetic_records())
    report = trace_report.main([path, "-o", str(tmp_path / "r.md")])
    assert os.path.exists(tmp_path / "r.md")
    for section in ["## Steps", "## Phases", "## Counters",
                    "## Gauges (last value)", "## Top ops", "## Bench phases",
                    "## Search progress"]:
        assert section in report, f"missing {section}"
    # first step reported separately; steady stats over the other 4
    assert "first step (incl. compile): 2000.0 ms" in report
    assert "steady-state over 4 steps" in report
    assert "golden-run" in report


def test_corrupt_tail_tolerated(tmp_path):
    path = str(tmp_path / "t.jsonl")
    write_trace(path, synthetic_records())
    with open(path, "a") as f:
        f.write('{"t": "span", "name": "tru')  # watchdog-killed mid-write
    report = trace_report.main([path])
    assert "## Steps" in report


def test_empty_trace(tmp_path):
    path = str(tmp_path / "e.jsonl")
    write_trace(path, [])
    report = trace_report.main([path])
    assert "no span/counter records" in report


def test_golden_output(tmp_path):
    """Byte-exact golden: regenerate with
    ``python tests/test_trace_report.py --regen`` after deliberate
    format changes."""
    path = str(tmp_path / "t.jsonl")
    write_trace(path, synthetic_records())
    report = trace_report.render_report(trace_report.parse_trace(path))
    with open(GOLDEN) as f:
        assert report == f.read()


if __name__ == "__main__" and "--regen" in sys.argv:
    import tempfile

    tmp = os.path.join(tempfile.mkdtemp(), "t.jsonl")
    write_trace(tmp, synthetic_records())
    os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
    with open(GOLDEN, "w") as f:
        f.write(trace_report.render_report(trace_report.parse_trace(tmp)))
    print(f"regenerated {GOLDEN}")
