"""Search flight-recorder + search_report CLI tests: the embedded
stdlib .pb reader against the canonical codec, a byte-exact golden
report from a seeded search (the report is a committed artifact format —
changes must be deliberate), every-op "why" coverage, strategy diffs,
pipeline-search events, and the zero-calls-when-disabled contract."""

import json
import os
import re
import sys

import pytest

sys.path.insert(0, ".")

import flexflow_tpu as ff
from flexflow_tpu.config import DeviceType, ParallelConfig
from flexflow_tpu.observability import events
from flexflow_tpu.observability.searchtrace import SearchRecorder, pc_str
from flexflow_tpu.parallel.strategy import save_strategies_to_file, \
    write_provenance
from flexflow_tpu.simulator.machine import TPUMachineModel
from flexflow_tpu.simulator.search import SearchResult, mcmc_search
from flexflow_tpu.tools import search_report

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "search_report.md")
SHIPPED = os.path.join(os.path.dirname(__file__), "..", "strategies")


@pytest.fixture(autouse=True)
def _isolated_singleton(monkeypatch):
    monkeypatch.delenv("FF_TELEMETRY", raising=False)
    monkeypatch.delenv("FF_TELEMETRY_FILE", raising=False)
    events.reset_active()
    yield
    events.reset_active()


def _mlp(batch=32, devices=8):
    # never compiled: searches run on the simulated machine only, like
    # tools/offline_search.py
    cfg = ff.FFConfig(batch_size=batch, workers_per_node=devices,
                      compute_dtype="float32")
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 16), nchw=False, name="x")
    t = m.dense(inp, 32, activation=ff.ActiMode.RELU, name="fc1")
    t = m.dense(t, 16, name="fc2")
    m.softmax(m.dense(t, 4, name="fc3"), name="sm")
    return m


def _seeded_search_trace(trace_path):
    """The golden fixture: a seeded tiny-budget alexnet search on the
    analytic cost model — fully deterministic, so the rendered report is
    too.  Alexnet (not the MLP) because its op costs differ enough that
    the anneal actually REJECTS proposals, exercising the metropolis
    path and the best-rejected-alternative tracking."""
    from flexflow_tpu.tools.offline_search import build_model

    os.environ["FF_TELEMETRY"] = "1"
    os.environ["FF_TELEMETRY_FILE"] = trace_path
    events.reset_active()
    try:
        m = build_model("alexnet", batch_size=64, num_devices=16)
        mm = TPUMachineModel.calibrated(num_devices=16)
        best = mcmc_search(m, budget=40, machine_model=mm, seed=3,
                           verbose=False)
    finally:
        events.reset_active()
        del os.environ["FF_TELEMETRY"]
        del os.environ["FF_TELEMETRY_FILE"]
    return best


# ---------------------------------------------------------------------------
# recorder + SearchResult
# ---------------------------------------------------------------------------

def test_pc_str():
    assert pc_str(ParallelConfig(dims=(4, 1, 2, 1))) == "4x1x2x1"
    assert pc_str(ParallelConfig.host_rowsparse(2)) == "host[1x1]"
    pc = ParallelConfig(dims=(2, 1)).with_device_ids((4, 5))
    assert pc_str(pc) == "2x1@4"
    assert pc_str(None) == "?"


def test_search_result_is_a_plain_dict():
    s = {"fc1": ParallelConfig(dims=(2, 1))}
    r = SearchResult(s, engine="mcmc", budget=10, seed=1, num_devices=8,
                     best_s=0.004, dp_s=0.009)
    assert dict(r) == s and r["fc1"].dims == (2, 1)
    assert r.engine == "mcmc" and r.best_s == 0.004 and r.dp_s == 0.009


def test_mcmc_search_returns_costs(tmp_path):
    m = _mlp()
    mm = TPUMachineModel.calibrated(num_devices=8)
    best = mcmc_search(m, budget=10, machine_model=mm, seed=0,
                       verbose=False)
    assert isinstance(best, SearchResult)
    assert best.engine == "mcmc" and best.seed == 0 and best.budget == 10
    assert best.best_s is not None and best.dp_s is not None
    assert 0 < best.best_s <= best.dp_s


def test_disabled_search_makes_zero_event_log_calls(monkeypatch):
    """No telemetry: the recorder is None and the search never touches
    the event log (any write would raise)."""
    monkeypatch.setattr(
        events.EventLog, "_write",
        lambda self, rec: (_ for _ in ()).throw(
            AssertionError(f"event-log call while disabled: {rec}")))
    assert SearchRecorder.maybe("mcmc", 10, 8) is None
    m = _mlp()
    mm = TPUMachineModel.calibrated(num_devices=8)
    best = mcmc_search(m, budget=10, machine_model=mm, seed=0,
                       verbose=False)
    assert best
    from flexflow_tpu.simulator.pipeline_search import search_pipeline
    search_pipeline(_mlp(), machine_model=mm)


def test_recorder_tracks_best_rejected_alternative(tmp_path):
    log = events.EventLog(str(tmp_path / "t.jsonl"), run_id="r")
    rec = SearchRecorder(log, "mcmc", budget=3, num_devices=4, seed=0)
    rec.start(initial_ms=10.0)
    a, b = ParallelConfig(dims=(1, 1)), ParallelConfig(dims=(4, 1))
    rec.candidate(0, "fc1", a, b, cur_ms=10.0, new_ms=8.0, best_ms=8.0,
                  accepted=True, reason="downhill")
    rec.candidate(1, "fc1", b, a, cur_ms=8.0, new_ms=9.5, best_ms=8.0,
                  accepted=False, reason="metropolis", prob=0.2)
    rec.candidate(2, "fc1", b, ParallelConfig(dims=(2, 2)), cur_ms=8.0,
                  new_ms=8.8, best_ms=8.0, accepted=False,
                  reason="metropolis", prob=0.4)
    rec.finish({"fc1": b, "fc2": a}, best_ms=8.0)
    log.close()
    with open(log.path) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    ops = {r["attrs"]["op"]: r["attrs"] for r in recs
           if r.get("name") == "search_op_summary"}
    # the best REJECTED alternative is the cheaper of the two rejects
    assert ops["fc1"]["alt"] == "2x2" and ops["fc1"]["alt_ms"] == 8.8
    assert ops["fc1"]["alt_delta_ms"] == pytest.approx(0.8)
    assert ops["fc1"]["gain_ms"] == pytest.approx(2.0)
    # fc2 never proposed, still summarized (the why table covers it)
    assert ops["fc2"]["proposals"] == 0 and ops["fc2"]["final"] == "1x1"
    summ = [r["attrs"] for r in recs if r.get("name") == "search_summary"]
    assert summ[0]["proposals"] == 3 and summ[0]["accepted"] == 1
    assert summ[0]["best_ms"] == 8.0 and summ[0]["last_improve_iter"] == 0


def test_compile_export_stamps_provenance(tmp_path, devices):
    """FFModel.compile() with a search budget + export writes the
    sidecar from the search's own cost — no re-simulation."""
    from flexflow_tpu.parallel.strategy import read_provenance

    out = str(tmp_path / "searched.pb")
    cfg = ff.FFConfig(batch_size=32, compute_dtype="float32",
                      search_budget=8, seed=5, export_strategy_file=out)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((32, 16), nchw=False, name="x")
    t = m.dense(inp, 32, activation=ff.ActiMode.RELU, name="fc1")
    m.softmax(m.dense(t, 4, name="fc2"), name="sm")
    m.compile(ff.SGDOptimizer(lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    meta = read_provenance(out)
    assert meta is not None
    assert meta["engine"] in ("native", "mcmc")
    assert meta["budget"] == 8 and meta["seed"] == 5
    assert meta["best_ms"] > 0  # carried from the search, not re-simulated
    ops = search_report.read_strategy_pb(out)
    assert set(meta["ops"]) == set(ops)  # attribution covers every op


# ---------------------------------------------------------------------------
# embedded .pb reader vs the canonical codec
# ---------------------------------------------------------------------------

def test_pb_reader_matches_canonical_codec(tmp_path):
    strategies = {
        "conv1": ParallelConfig(dims=(4, 1, 2, 1)),
        # >127 partitions forces multi-byte varints through the reader
        "wide": ParallelConfig(dims=(200, 1),
                               device_ids=tuple(range(200))),
        "offset": ParallelConfig(dims=(2, 1), device_ids=(4, 5)),
        "table": ParallelConfig.host_rowsparse(2),
        "cpu_op": ParallelConfig(device_type=DeviceType.CPU,
                                 dims=(1, 1), device_ids=(0,)),
    }
    path = str(tmp_path / "s.pb")
    save_strategies_to_file(path, strategies)
    parsed = search_report.read_strategy_pb(path)
    assert set(parsed) == set(strategies)
    for name, pc in strategies.items():
        rec = parsed[name]
        assert tuple(rec["dims"]) == pc.dims, name
        assert tuple(rec["ids"]) == pc.device_ids, name
        # and the compact rendering matches the recorder's pc_str, so
        # diff rows and trace events read identically
        assert search_report.config_str(rec) == pc_str(pc), name


# ---------------------------------------------------------------------------
# trace-mode report
# ---------------------------------------------------------------------------

def test_report_every_op_has_why_row(tmp_path):
    trace = str(tmp_path / "search.jsonl")
    best = _seeded_search_trace(trace)
    report = search_report.render_search_report(
        search_report.parse_trace(trace))
    assert "## Search: mcmc" in report
    assert "### Convergence" in report
    assert "## Why this config" in report
    why = report[report.index("## Why this config"):]
    for op in best:  # EVERY op in the final strategy gets a why row
        assert f"| {op} | {pc_str(best[op])} |" in why, op
    assert "acceptance rate by quarter:" in report


def test_report_empty_and_corrupt_trace(tmp_path):
    p = str(tmp_path / "e.jsonl")
    with open(p, "w") as f:
        f.write("\n{not json\n")
    report = search_report.render_search_report(
        search_report.parse_trace(p))
    assert "no search events in trace" in report


def test_pipeline_search_emits_span_and_plan_events(tmp_path, monkeypatch):
    trace = tmp_path / "p.jsonl"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    from flexflow_tpu.simulator.pipeline_search import search_pipeline

    m = _mlp()
    mm = TPUMachineModel.calibrated(num_devices=8)
    plan = search_pipeline(m, machine_model=mm)
    events.reset_active()
    with open(trace) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    spans = [r for r in recs if r["t"] == "span"
             and r["name"] == "pipeline_search"]
    assert spans and "plans" in spans[0]["attrs"]
    cands = [r["attrs"] for r in recs if r.get("name") == "search_candidate"]
    if plan is not None:  # grid produced plans -> each one recorded
        assert cands and all(c["op"] == "<pipeline>" for c in cands)
        assert any(c["accepted"] for c in cands)
        report = search_report.render_search_report(recs)
        assert "### Pipeline plans" in report
        assert f"S{plan['num_stages']}xdp{plan['dp_degree']}" in report


def _normalize(report):
    """Mask the one wall-clock-dependent value (search throughput) —
    everything else in the report is seed-deterministic."""
    return re.sub(r"(- throughput )\S+( proposals/s)",
                  r"\g<1>N\g<2>", report)


def test_golden_output(tmp_path):
    """Byte-exact golden (modulo the masked throughput number):
    regenerate with ``python tests/test_search_report.py --regen`` after
    deliberate format changes.  Also the population-engine guard: a
    single-chain trace has no chain/exchange/crossover events, so the
    population sections must not render (the golden would catch them)."""
    trace = str(tmp_path / "search.jsonl")
    _seeded_search_trace(trace)
    report = search_report.render_search_report(
        search_report.parse_trace(trace))
    with open(GOLDEN) as f:
        assert _normalize(report) == f.read()


def test_population_trace_renders_population_sections(tmp_path):
    """A population run's trace gains the per-chain / exchange /
    crossover sections; they render from the candidate ``chain`` tags
    and the search_exchange / search_crossover events."""
    from flexflow_tpu.simulator.population import (PopulationKnobs,
                                                   population_search)
    from flexflow_tpu.tools.offline_search import build_model

    trace = str(tmp_path / "pop.jsonl")
    os.environ["FF_TELEMETRY"] = "1"
    os.environ["FF_TELEMETRY_FILE"] = trace
    events.reset_active()
    try:
        m = build_model("alexnet", batch_size=64, num_devices=16)
        knobs = PopulationKnobs(population=4, exchange_every=5,
                                crossover_every=10, learned=False)
        population_search(m, budget=300, seed=3, verbose=False,
                          knobs=knobs)
    finally:
        events.reset_active()
        del os.environ["FF_TELEMETRY"]
        del os.environ["FF_TELEMETRY_FILE"]
    report = search_report.render_search_report(
        search_report.parse_trace(trace))
    assert "## Search: population" in report
    assert "### Per-chain convergence" in report
    assert "### Replica exchange (by temperature pair)" in report
    # every chain shows a row
    for ci in range(4):
        assert re.search(rf"^\| {ci} \| \d+ \| \d+", report, re.M)
    # crossover attempts (if any spliced) render a lineage table; the
    # section is event-gated, so only assert when events exist
    recs = search_report.parse_trace(trace)
    if any(r.get("name") == "search_crossover" for r in recs
           if r.get("t") == "event"):
        assert "### Crossover lineage" in report


# ---------------------------------------------------------------------------
# diff mode
# ---------------------------------------------------------------------------

def _fake_sidecar(path, best_ms, op_ms):
    write_provenance(path, {
        "engine": "mcmc", "budget": 100, "seed": 0, "num_devices": 8,
        "best_ms": best_ms,
        "ops": {op: {"dims": "?", "parts": 1, "host": False,
                     "fwd_ms": ms, "bwd_ms": ms} for op, ms in op_ms.items()},
    })


def test_diff_names_changed_ops_with_cost_impact(tmp_path):
    a = {"fc1": ParallelConfig(dims=(8, 1)),
         "fc2": ParallelConfig(dims=(1, 1)),
         "gone": ParallelConfig(dims=(1, 1))}
    b = {"fc1": ParallelConfig(dims=(8, 1)),   # unchanged
         "fc2": ParallelConfig(dims=(4, 2)),   # changed
         "new": ParallelConfig(dims=(2, 1))}
    ap, bp = str(tmp_path / "a.pb"), str(tmp_path / "b.pb")
    save_strategies_to_file(ap, a)
    save_strategies_to_file(bp, b)
    _fake_sidecar(ap, best_ms=9.0, op_ms={"fc1": 1.0, "fc2": 3.0})
    _fake_sidecar(bp, best_ms=7.5, op_ms={"fc1": 1.0, "fc2": 2.0})
    report = search_report.render_diff(ap, bp)
    assert "a sidecar: ok" in report and "b sidecar: ok" in report
    assert "- ops only in a: gone" in report
    assert "- ops only in b: new" in report
    assert "- 1 changed / 1 unchanged ops" in report
    assert "| fc2 | 1x1 | 4x2 | 6.000 | 4.000 | -2.000 |" in report
    assert "9.000 ms (a) vs 7.500 ms (b) (-1.500 ms)" in report
    assert "fc1 | 8x1 | 8x1" not in report  # unchanged ops not listed


def test_diff_tolerates_missing_and_corrupt_sidecars(tmp_path):
    a = {"fc1": ParallelConfig(dims=(8, 1))}
    b = {"fc1": ParallelConfig(dims=(2, 4))}
    ap, bp = str(tmp_path / "a.pb"), str(tmp_path / "b.pb")
    save_strategies_to_file(ap, a)
    save_strategies_to_file(bp, b)
    with open(bp + ".meta.json", "w") as f:
        f.write('{"truncated')
    report = search_report.render_diff(ap, bp)
    assert "a sidecar: missing" in report
    assert "b sidecar: corrupt" in report
    assert "| fc1 | 8x1 | 2x4 | — | — | — |" in report


def test_diff_shipped_strategies(tmp_path):
    """The acceptance check: --diff on two shipped strategy files names
    the changed ops (no sidecars shipped -> config-only diff)."""
    shipped = os.path.join(SHIPPED, "alexnet_16.pb")
    ops = search_report.read_strategy_pb(shipped)
    assert len(ops) >= 10  # the full alexnet op list parses
    # perturb one op through the canonical codec and diff against it
    from flexflow_tpu.parallel.strategy import load_strategies_from_file
    s = load_strategies_from_file(shipped)
    s["conv1"] = ParallelConfig(dims=(16, 1, 1, 1),
                                device_ids=tuple(range(16)))
    other = str(tmp_path / "alexnet_new.pb")
    save_strategies_to_file(other, s)
    report = search_report.main(["--diff", shipped, other,
                                 "-o", str(tmp_path / "d.md")])
    assert "- 1 changed /" in report
    assert "| conv1 |" in report and "16x1x1x1" in report


if __name__ == "__main__" and "--regen" in sys.argv:
    import tempfile

    tmp = os.path.join(tempfile.mkdtemp(), "search.jsonl")
    _seeded_search_trace(tmp)
    os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
    with open(GOLDEN, "w") as f:
        f.write(_normalize(search_report.render_search_report(
            search_report.parse_trace(tmp))))
    print(f"regenerated {GOLDEN}")
