"""Population search engine tests (ISSUE 15).

Four contracts pinned here:

  * a seeded ``population_search`` is bitwise-reproducible (same
    strategy map, same floats, same stats) — everything is driven by
    seeded RNGs in a fixed order;
  * the single-chain ``mcmc_search`` at default knobs is BITWISE
    identical to the pre-population code: exact best_s/dp_s floats and
    strategy fingerprints captured at the commit before this engine
    landed.  The population engine must not perturb the single-chain
    RNG stream, cost tiers, or proposal order;
  * crossover children are costed via delta patches — a child with K
    spliced ops charges exactly K proposals against the shared budget
    (never a rebuild, never free);
  * the learned cost tier only replaces the analytic roofline for op
    families that beat it under out-of-fold cross-validation, and the
    warm-start loader only trusts strategy files whose provenance
    sidecar matches (content hash, device count, op coverage).
"""

import json
import os
import shutil
import sys

import pytest

sys.path.insert(0, ".")

from flexflow_tpu.parallel.strategy import (load_warm_starts,
                                            strategies_fingerprint)
from flexflow_tpu.simulator.cost_model import (CostModel, LearnedCostTier,
                                               _key_flops_bytes,
                                               _parse_cost_key)
from flexflow_tpu.simulator.machine import TPUMachineModel
from flexflow_tpu.simulator.population import (PopulationKnobs,
                                               parse_learned_flag,
                                               population_search)
from flexflow_tpu.simulator.search import mcmc_search
from flexflow_tpu.tools.offline_search import build_model

STRATEGIES = os.path.join(os.path.dirname(__file__), "..", "strategies")


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def test_knobs_from_env_defaults_and_overrides():
    k = PopulationKnobs.from_env(env={})
    assert (k.population, k.exchange_every, k.crossover_every) == (8, 50, 150)
    assert k.learned is None
    k = PopulationKnobs.from_env(env={"FF_SEARCH_POPULATION": "3",
                                      "FF_SEARCH_LADDER": "1,0.5,0.25",
                                      "FF_SEARCH_EXCHANGE": "0",
                                      "FF_SEARCH_LEARNED": "0"})
    assert k.population == 3 and k.ladder == (1.0, 0.5, 0.25)
    assert k.exchange_every == 0 and k.learned is False
    assert k.alphas(0.04) == (0.04, 0.02, 0.01)
    # geometric ladder when no explicit list
    k = PopulationKnobs.from_env(env={"FF_SEARCH_LADDER": "0.5"})
    assert k.alphas(0.08)[:3] == (0.08, 0.04, 0.02)


@pytest.mark.parametrize("env", [
    {"FF_SEARCH_POPULATION": "1"},
    {"FF_SEARCH_POPULATION": "zebra"},
    {"FF_SEARCH_LADDER": "1.5"},              # ratio > 1
    {"FF_SEARCH_LADDER": "1,0.5"},            # len != population
    {"FF_SEARCH_LADDER": "0.5,-1", "FF_SEARCH_POPULATION": "2"},
    {"FF_SEARCH_EXCHANGE": "-1"},
    {"FF_SEARCH_LEARNED": "maybe"},
])
def test_knobs_bad_env_is_loud(env):
    with pytest.raises(ValueError):
        PopulationKnobs.from_env(env=env)


def test_parse_learned_flag_tristate():
    assert parse_learned_flag("") is None
    assert parse_learned_flag("0") is False
    assert parse_learned_flag("on") is True
    with pytest.raises(ValueError):
        parse_learned_flag("2")


# ---------------------------------------------------------------------------
# population engine
# ---------------------------------------------------------------------------

def _pop(budget=400, seed=3, **kw):
    knobs = PopulationKnobs(**{"population": 4, "exchange_every": 10,
                               "crossover_every": 20, "learned": False,
                               **kw})
    m = build_model("alexnet", 64, 16)
    return population_search(m, budget=budget, seed=seed, verbose=False,
                             knobs=knobs)


def test_population_seeded_run_is_bitwise_reproducible():
    a = _pop()
    b = _pop()
    assert dict(a) == dict(b)
    assert a.best_s == b.best_s and a.dp_s == b.dp_s
    assert a.chains == b.chains
    assert a.stats == b.stats
    assert strategies_fingerprint(dict(a)) == strategies_fingerprint(dict(b))


def test_population_result_shape_and_budget():
    r = _pop(budget=300)
    assert r.engine == "population"
    assert len(r.chains) == 4
    assert {c["seed"].split(":")[0] for c in r.chains} <= \
        {"dp", "sidecar", "random"}
    assert r.chains[0]["seed"] == "dp"
    # fair accounting: every costed candidate — chain proposals AND
    # crossover patches — charges the one shared budget
    spent = r.stats["spent"]
    assert spent <= 300
    assert sum(c["proposals"] for c in r.chains) \
        + r.stats["crossover"]["patches"] == spent
    # the returned best is the best any chain ever saw
    assert r.best_s * 1e3 <= min(c["best_ms"] for c in r.chains) + 1e-6
    assert r.best_s <= r.dp_s


def test_crossover_child_costs_exactly_k_patches():
    # crossover every round: attempts must happen, and each attempt's
    # patch count lands in the shared budget accounting
    r = _pop(budget=200, crossover_every=1, exchange_every=0)
    cs = r.stats["crossover"]
    assert cs["attempts"] >= 1
    assert cs["patches"] >= cs["attempts"]  # every attempt splices >= 1 op
    assert sum(c["proposals"] for c in r.chains) + cs["patches"] \
        == r.stats["spent"] <= 200
    # adopted lineage entries record parents, child chain and K
    for rec in r.stats["lineage"]:
        assert rec["patches"] >= 1 and rec["chain"] in range(4)


def test_exchange_stats_cover_adjacent_pairs():
    r = _pop(budget=400, exchange_every=5, crossover_every=0)
    assert set(r.stats["exchange"]) == {"0<->1", "1<->2", "2<->3"}
    for st in r.stats["exchange"].values():
        assert st["attempts"] >= 1 and 0 <= st["accepts"] <= st["attempts"]


def test_population_no_worse_than_dp_and_tracks_winner():
    r = _pop(budget=600)
    w = r.stats["winner_chain"]
    assert r.chains[w]["best_ms"] == min(c["best_ms"] for c in r.chains)


def test_full_sim_escape_hatch_matches_delta(monkeypatch):
    monkeypatch.setenv("FF_SIM_DELTA", "0")
    full = _pop(budget=120)
    monkeypatch.delenv("FF_SIM_DELTA")
    delta = _pop(budget=120)
    assert not full.stats["delta_sim"] and delta.stats["delta_sim"]
    # same seeded walk, same floats — the delta path's bitwise-equality
    # contract extends through the population engine
    assert dict(full) == dict(delta)
    assert full.best_s == delta.best_s


# ---------------------------------------------------------------------------
# single-chain bitwise identity (pre-population goldens)
# ---------------------------------------------------------------------------

# Captured at the commit immediately before the population engine
# landed: mcmc_search(build_model(name, 64, nd), budget, seed) on the
# calibrated machine.  Any drift in these floats means the single-chain
# RNG stream or cost tiers changed — a release-breaking regression.
# dlrm best_s re-captured when the cost model started charging DCN
# bandwidth for non-sample dims spilling onto the host axis: the search
# converges to the same strategy (fingerprint and dp_s unchanged) but
# its best cost now includes the spill surcharge.
SINGLE_CHAIN_GOLDENS = [
    ("alexnet", 16, 300, 3,
     0.00388669815776176, 0.01863936267427486,
     "sha256:1dd6a00fcccd3c077c5835ded51dd71c56f8eb232be75f6c9134e4c886574074"),
    ("transformer", 64, 200, 0,
     0.013445108752907626, 0.014559030250737392,
     "sha256:5569e1894349173d188a2095401cf2d7f0bae14ec12c1957cb96db93193965de"),
    ("dlrm", 64, 200, 1,
     0.0021526604546714405, 0.015924557452834633,
     "sha256:9cfb2a7f16224253e8eb70aeaa412a3a392c2ed35beb01cf8da6f7f2832c85f0"),
]


@pytest.mark.parametrize("name,nd,budget,seed,best_s,dp_s,fp",
                         SINGLE_CHAIN_GOLDENS,
                         ids=[g[0] for g in SINGLE_CHAIN_GOLDENS])
def test_single_chain_bitwise_identical_to_pre_population(
        name, nd, budget, seed, best_s, dp_s, fp):
    m = build_model(name, 64, nd)
    r = mcmc_search(m, budget=budget, seed=seed, verbose=False)
    assert r.best_s == best_s          # exact: bitwise, not approx
    assert r.dp_s == dp_s
    assert strategies_fingerprint(dict(r)) == fp


# ---------------------------------------------------------------------------
# learned cost tier
# ---------------------------------------------------------------------------

def _dense_corpus(fn, n=8):
    """Synthetic Dense-family corpus: n shapes x {forward, backward},
    with times assigned by ``fn(flops, bytes, which)``."""
    mm = TPUMachineModel.calibrated(num_devices=8)
    probe = LearnedCostTier(mm, corpus={})
    corpus = {}
    for i in range(n):
        b, din, dout = 64 * (i + 1), 256 * (i + 1), 128 * (i + 2)
        for which in ("forward", "backward"):
            key = f"Dense:({b}, {dout}):(({b}, {din}),)::float32:{which}"
            fam, sub, ins, extra, _d, w = _parse_cost_key(key)
            fl, by = _key_flops_bytes(fam, sub, ins, extra, 4.0)
            corpus[key] = fn(probe, fl, by, which)
    return mm, corpus


def test_learned_tier_falls_back_when_analytic_wins_oof():
    # times ARE the analytic roofline -> analytic OOF error is zero, the
    # regression cannot strictly beat it -> family rejected, predictions
    # fall through to the roofline
    mm, corpus = _dense_corpus(
        lambda p, fl, by, w: p._analytic_key("Dense", fl, by, w))
    tier = LearnedCostTier(mm, corpus=corpus)
    fam = tier.provenance["families"]["Dense"]
    assert fam["points"] == 16 and fam["used"] is False
    assert fam["reason"] == "analytic roofline wins out-of-fold"
    assert tier.provenance["used_families"] == []
    assert tier.predict(next(iter(corpus))) is None


def test_learned_tier_used_when_it_wins_oof():
    # times exactly log-linear in the features (and far from the
    # roofline) -> the fit wins out-of-fold and serves predictions
    mm, corpus = _dense_corpus(
        lambda p, fl, by, w: 3e-6 * (1.0 + fl) ** 0.3
        * (2.0 if w == "backward" else 1.0))
    tier = LearnedCostTier(mm, corpus=corpus)
    fam = tier.provenance["families"]["Dense"]
    assert fam["used"] is True
    assert fam["oof_log_rmse_learned"] < fam["oof_log_rmse_analytic"]
    assert tier.provenance["used_families"] == ["Dense"]
    key = next(iter(corpus))
    assert tier.predict(key) == pytest.approx(corpus[key], rel=0.05)
    # provenance reports BOTH out-of-fold errors (acceptance criterion)
    assert {"oof_log_rmse_learned", "oof_log_rmse_analytic",
            "folds"} <= set(fam)


def test_learned_tier_below_threshold_never_fits():
    mm, corpus = _dense_corpus(lambda p, fl, by, w: 1e-5, n=4)  # 8 points
    tier = LearnedCostTier(mm, corpus=corpus)
    fam = tier.provenance["families"]["Dense"]
    assert fam["used"] is False and "threshold" in fam["reason"]


def test_cost_model_learned_tier_slots_before_analytic():
    mm, corpus = _dense_corpus(lambda p, fl, by, w: 4.2e-5)
    tier = LearnedCostTier(mm, corpus=corpus)
    assert tier.provenance["used_families"] == ["Dense"]
    cost = CostModel(mm, measure=False, compute_dtype="float32")
    cost.attach_learned_tier(tier)
    m = build_model("alexnet", 64, 8)
    fc = next(op for op in m.ops if op._type == "Dense")
    from flexflow_tpu.config import ParallelConfig
    pc = fc.legalize_pc(ParallelConfig(dims=(8, 1)))
    before = cost.stats["learned"]
    cost.op_time(fc, pc, "forward")
    assert cost.stats["learned"] == before + 1
    # once any op is priced the memo is warm: attaching then would
    # serve mixed tiers from one cache — refused loudly
    with pytest.raises(AssertionError):
        cost.attach_learned_tier(tier)


def test_population_default_learned_tier_recorded_in_stats():
    # engine default (knobs.learned None) turns the tier on and stamps
    # provenance; the shipped corpus has CV-winning families today
    m = build_model("alexnet", 64, 16)
    r = population_search(m, budget=60, seed=0, verbose=False,
                          knobs=PopulationKnobs(population=2,
                                                exchange_every=0,
                                                crossover_every=0))
    prov = r.stats["learned"]
    assert prov is not None and prov["tier"] == "learned"
    assert prov["corpus_points"] >= 12
    for fam in prov["used_families"]:
        assert prov["families"][fam]["used"] is True


# ---------------------------------------------------------------------------
# warm-start loader vs the shipped sidecars
# ---------------------------------------------------------------------------

def test_warm_starts_load_shipped_alexnet_sidecar():
    m = build_model("alexnet", 64, 16)
    warm = load_warm_starts(m, 16, strategies_dir=STRATEGIES)
    labels = [label for label, _ in warm]
    assert "alexnet_16.pb" in labels
    strategies = dict(warm)["alexnet_16.pb"]
    op_names = {op.name for op in m.ops}
    assert set(strategies) <= op_names and strategies
    # and the population engine actually seeds a chain from it
    r = population_search(m, budget=40, seed=0, verbose=False,
                          knobs=PopulationKnobs(population=2,
                                                exchange_every=0,
                                                crossover_every=0,
                                                learned=False))
    assert r.chains[1]["seed"] == "sidecar:alexnet_16.pb"


def test_warm_starts_skip_device_mismatch_and_foreign_models():
    m = build_model("alexnet", 64, 8)  # sidecars are all num_devices=16
    assert load_warm_starts(m, 8, strategies_dir=STRATEGIES) == []
    m = build_model("transformer", 64, 16)  # no .pb covers these ops
    assert load_warm_starts(m, 16, strategies_dir=STRATEGIES) == []


def test_warm_starts_stale_sidecar_warns_and_skips(tmp_path):
    src = os.path.join(STRATEGIES, "alexnet_16.pb")
    dst = str(tmp_path / "alexnet_16.pb")
    shutil.copy(src, dst)
    shutil.copy(src + ".meta.json", dst + ".meta.json")
    with open(dst + ".meta.json") as f:
        meta = json.load(f)
    meta["content_hash"] = "sha256:" + "0" * 64  # .pb edited after stamping
    with open(dst + ".meta.json", "w") as f:
        json.dump(meta, f)
    m = build_model("alexnet", 64, 16)
    with pytest.warns(UserWarning, match="stale"):
        assert load_warm_starts(m, 16, strategies_dir=str(tmp_path)) == []


def test_warm_starts_missing_sidecar_is_silently_skipped(tmp_path):
    shutil.copy(os.path.join(STRATEGIES, "alexnet_16.pb"),
                str(tmp_path / "alexnet_16.pb"))
    m = build_model("alexnet", 64, 16)
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any warning fails the test
        assert load_warm_starts(m, 16, strategies_dir=str(tmp_path)) == []


def test_shipped_sidecars_are_fresh():
    # the repo's own strategies/ must never ship a stale sidecar
    from flexflow_tpu.tools.search_report import read_sidecar

    pbs = [f for f in os.listdir(STRATEGIES) if f.endswith(".pb")]
    assert pbs
    for f in pbs:
        meta, status = read_sidecar(os.path.join(STRATEGIES, f))
        assert status == "ok", (f, status)
        assert meta["num_devices"] == 16
