"""Resharding between differently-partitioned adjacent ops.

The reference's core magic: op A under config X feeds op B under config Y
and Legion moves the data (SURVEY.md §7 'hard parts').  Here GSPMD does
the movement; each pair below trains the test net with a DIFFERENT config
transition on one edge and must match single-device numerics exactly
(up to float reassociation)."""

import numpy as np
import pytest

import flexflow_tpu as ff
from tests.test_sharding import DP8, SINGLE, build_and_train

# (producer name, producer config, consumer name, consumer config) —
# transitions covering dp→spatial, spatial→dp, dp→tp, tp→dp, tp→tp,
# sample-split changes, and the 4D→2D flat boundary.
PAIRS = [
    ("conv1", (8, 1, 1, 1), "pool1", (2, 2, 2, 1)),   # dp -> spatial
    ("conv1", (2, 2, 2, 1), "pool1", (8, 1, 1, 1)),   # spatial -> dp
    ("conv1", (1, 4, 2, 1), "pool1", (4, 1, 1, 1)),   # pure spatial -> dp4
    ("fc1", (8, 1), "fc2", (2, 4)),                   # dp -> tensor parallel
    ("fc1", (2, 4), "fc2", (4, 2)),                   # tp -> different tp
    ("flat1", (2, 1), "fc1", (1, 8)),                 # sample2 -> pure tp
]


@pytest.fixture(scope="module")
def single_baseline(devices):
    return build_and_train(SINGLE)[:2]


@pytest.mark.parametrize("pair", PAIRS,
                         ids=[f"{a}{x}->{b}{y}" for a, x, b, y in PAIRS])
def test_resharding_pair_matches_single_device(devices, single_baseline, pair):
    prod, pcfg, cons, ccfg = pair
    strategies = dict(DP8)
    strategies[prod] = ff.ParallelConfig(dims=pcfg)
    strategies[cons] = ff.ParallelConfig(dims=ccfg)
    fc2, conv1, _ = build_and_train(strategies)
    fc2_a, conv_a = single_baseline
    np.testing.assert_allclose(fc2_a, fc2, rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(conv_a, conv1, rtol=5e-4, atol=5e-5)
