"""REAL multi-process execution: 2 controllers, one logical mesh.

The reference exercises multi-node only on physical clusters (Summit
jsrun scripts — SURVEY §4.5); here the multi-controller runtime is
spawned in CI: two OS processes × 4 virtual CPU devices each form one
8-device dcn×ici mesh via ``jax.distributed.initialize`` (the
GASNet-startup analogue), each process feeds its host-local half of the
global batch (``host_local_batch`` ≈ DataParallelShardingFunctor,
model.cc:1361-1370), and training numerics must equal a single-process
run on the same global batch.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = """
import os, sys
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
sys.path.insert(0, {root!r})
import flexflow_tpu as ff
from flexflow_tpu.parallel import distributed as dist

dist.initialize()  # reads COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID
pid = jax.process_index()
assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 4
assert jax.device_count() == 8

cfg = ff.FFConfig(batch_size=16, workers_per_node=4, num_nodes=2)
m = ff.FFModel(cfg)
inp = m.create_tensor((16, 8), nchw=False, name='input')
t = m.dense(inp, 16, activation='relu', name='fc1')
t = m.dense(t, 4, name='fc2')
m.softmax(t, name='sm')
m.compile(ff.SGDOptimizer(lr=0.5), 'sparse_categorical_crossentropy',
          ['accuracy'])
assert m.machine.axis_names[0] == 'dcn', m.machine.axis_names
m.init_layers(seed=5)

rng = np.random.default_rng(0)
X = rng.standard_normal((16, 8), dtype=np.float32)   # the GLOBAL batch
Y = np.argmax(X[:, :4], 1).astype(np.int32)[:, None]
half = 8
lo, hi = pid * half, (pid + 1) * half
for _ in range(5):
    m.set_batch({{inp: X[lo:hi]}}, Y[lo:hi])   # host-LOCAL shard
    m.train_iteration()
m.sync()
k1 = m.get_parameter('fc1', 'kernel')
k2 = m.get_parameter('fc2', 'kernel')
print('FPRINT', pid, float(np.sum(np.abs(k1))), float(np.sum(k1 * k1)),
      float(np.sum(np.abs(k2))), flush=True)
dist.shutdown()
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_two_controllers(child_src):
    """Spawn 2 coordinated controller processes (4 virtual CPU devices
    each); returns ({pid: fingerprint tuple}, skipped?)."""
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["NUM_PROCESSES"] = "2"
        env["PROCESS_ID"] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", child_src.format(root=_ROOT)],
            env=env, cwd=_ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    fprints, skipped = {}, False
    try:
        for pid, p in enumerate(procs):
            out, err = p.communicate(timeout=600)
            assert p.returncode == 0, f"proc {pid} failed:\n{err[-3000:]}"
            if any(l.startswith("PIPESKIP") for l in out.splitlines()):
                skipped = True
                continue
            line = [l for l in out.splitlines() if l.startswith("FPRINT")][0]
            fprints[pid] = tuple(float(v) for v in line.split()[2:])
    finally:
        for p in procs:  # a failed/hung sibling must not outlive the test
            if p.poll() is None:
                p.kill()
    return fprints, skipped


@pytest.mark.slow
def test_two_process_training_matches_single_process(devices):
    fprints, _ = _run_two_controllers(_CHILD)

    # both controllers hold identical (replicated) trained weights
    np.testing.assert_allclose(fprints[0], fprints[1], rtol=1e-5)

    # and they match the single-process run on the same global batch
    import flexflow_tpu as ff

    cfg = ff.FFConfig(batch_size=16, workers_per_node=8)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False, name="input")
    t = m.dense(inp, 16, activation="relu", name="fc1")
    t = m.dense(t, 4, name="fc2")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(lr=0.5), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=5)
    rng = np.random.default_rng(0)
    X = rng.standard_normal((16, 8), dtype=np.float32)
    Y = np.argmax(X[:, :4], 1).astype(np.int32)[:, None]
    for _ in range(5):
        m.set_batch({inp: X}, Y)
        m.train_iteration()
    m.sync()
    k1 = m.get_parameter("fc1", "kernel")
    k2 = m.get_parameter("fc2", "kernel")
    ref = (float(np.sum(np.abs(k1))), float(np.sum(k1 * k1)),
           float(np.sum(np.abs(k2))))
    np.testing.assert_allclose(fprints[0], ref, rtol=1e-4, atol=1e-6)


_CHILD_PIPE = """
import os, sys
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
sys.path.insert(0, {root!r})
import flexflow_tpu as ff
from flexflow_tpu.parallel import distributed as dist

dist.initialize()
pid = jax.process_index()
assert jax.device_count() == 8

cfg = ff.FFConfig(batch_size=16, workers_per_node=4, num_nodes=2)
m = ff.FFModel(cfg)
inp = m.create_tensor((16, 8), nchw=False, name='input')
t = m.dense(inp, 24, activation='relu', name='fc1')
t = m.dense(t, 24, activation='relu', name='fc2')
t = m.dense(t, 24, activation='relu', name='fc3')
t = m.dense(t, 4, name='fc4')
m.softmax(t, name='sm')
m.set_pipeline(num_stages=2, num_microbatches=4, dp_degree=2)
m.compile(ff.SGDOptimizer(lr=0.5), 'sparse_categorical_crossentropy',
          ['accuracy'])
if m._pipeline_plan is None:
    print('PIPESKIP', pid, flush=True)
    dist.shutdown()
    sys.exit(0)
m.init_layers(seed=5)

rng = np.random.default_rng(0)
X = rng.standard_normal((16, 8), dtype=np.float32)
Y = np.argmax(X[:, :4], 1).astype(np.int32)[:, None]
half = 8
lo, hi = pid * half, (pid + 1) * half
for _ in range(4):
    m.set_batch({{inp: X[lo:hi]}}, Y[lo:hi])
    m.train_iteration()
m.sync()
k1 = m.get_parameter('fc1', 'kernel')
k3 = m.get_parameter('fc3', 'kernel')
print('FPRINT', pid, float(np.sum(np.abs(k1))), float(np.sum(k1 * k1)),
      float(np.sum(np.abs(k3))), flush=True)
dist.shutdown()
"""


@pytest.mark.slow
def test_two_process_pipeline_training(devices):
    """REAL 2-process execution of the GPipe pipeline: dp over the DCN
    axis x pp over each host's local devices, packed stage weights;
    both controllers converge to identical replicated fingerprints AND
    match a single-process run of the same pipeline on the same global
    batch (guards the microbatch numerics, not just SPMD agreement)."""
    fprints, skipped = _run_two_controllers(_CHILD_PIPE)
    if skipped:
        pytest.skip("pipeline plan not expressible on the dcn x ici mesh")
    np.testing.assert_allclose(fprints[0], fprints[1], rtol=1e-5)

    import flexflow_tpu as ff

    cfg = ff.FFConfig(batch_size=16, workers_per_node=8)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False, name="input")
    t = m.dense(inp, 24, activation="relu", name="fc1")
    t = m.dense(t, 24, activation="relu", name="fc2")
    t = m.dense(t, 24, activation="relu", name="fc3")
    t = m.dense(t, 4, name="fc4")
    m.softmax(t, name="sm")
    m.set_pipeline(num_stages=2, num_microbatches=4, dp_degree=2)
    m.compile(ff.SGDOptimizer(lr=0.5), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=5)
    rng = np.random.default_rng(0)
    X = rng.standard_normal((16, 8), dtype=np.float32)
    Y = np.argmax(X[:, :4], 1).astype(np.int32)[:, None]
    for _ in range(4):
        m.set_batch({inp: X}, Y)
        m.train_iteration()
    m.sync()
    k1 = m.get_parameter("fc1", "kernel")
    k3 = m.get_parameter("fc3", "kernel")
    ref = (float(np.sum(np.abs(k1))), float(np.sum(k1 * k1)),
           float(np.sum(np.abs(k3))))
    np.testing.assert_allclose(fprints[0], ref, rtol=1e-4)


_CHILD_SPARSE = """
import os, sys
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
sys.path.insert(0, {root!r})
import flexflow_tpu as ff
from flexflow_tpu.config import DeviceType
from flexflow_tpu.parallel import distributed as dist

dist.initialize()
pid = jax.process_index()
assert jax.process_count() == 2

cfg = ff.FFConfig(batch_size=16, workers_per_node=4, num_nodes=2)
cfg.strategies['emb'] = ff.ParallelConfig(DeviceType.CPU, (1, 1), (0,))
m = ff.FFModel(cfg)
ids = m.create_tensor((16, 4), dtype='int32', name='ids')
t = m.embedding(ids, 1000, 8, name='emb')
t = m.dense(t, 4, name='head')
m.softmax(t, name='sm')
m.compile(ff.SGDOptimizer(m, lr=0.1),
          ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
          [ff.MetricsType.ACCURACY])
m.init_layers(seed=11)
assert 'emb' in m._host_embed, 'row-sparse path not taken multi-process'
info = m._host_embed['emb']
assert (info['row_lo'], info['row_hi']) == (pid * 500, (pid + 1) * 500)
assert m._params['emb']['weight'].shape[0] == 500  # own shard only

rng = np.random.default_rng(0)
X = rng.integers(0, 1000, (16, 4)).astype(np.int32)   # the GLOBAL batch
Y = (X[:, 0] % 4).astype(np.int32)[:, None]
half = 8
lo, hi = pid * half, (pid + 1) * half
for _ in range(6):
    m.set_batch({{ids: X[lo:hi]}}, Y[lo:hi])   # host-LOCAL shard
    m.train_iteration()
m.sync()
w = m.get_parameter('emb', 'weight')   # accessor assembles the FULL table
h = m.get_parameter('head', 'kernel')
assert w.shape[0] == 1000, w.shape
print('FPRINT', pid, float(np.sum(np.abs(w))), float(np.sum(w * w)),
      float(np.sum(np.abs(h))), flush=True)
dist.shutdown()
"""


@pytest.mark.slow
def test_two_process_row_sparse_host_embeddings(devices):
    """REAL 2-process row-sparse host embeddings: each host owns a row
    range of the table (reference run_summit.sh multi-node CPU-embedding
    DLRM), the compact row space is global, grads psum across hosts, and
    each host lazily updates only its owned rows.  Both controllers'
    ASSEMBLED tables agree AND match a single-process run on the same
    global batch."""
    fprints, _ = _run_two_controllers(_CHILD_SPARSE)
    np.testing.assert_allclose(fprints[0], fprints[1], rtol=1e-5)

    import flexflow_tpu as ff
    from flexflow_tpu.config import DeviceType

    cfg = ff.FFConfig(batch_size=16, workers_per_node=8)
    cfg.strategies["emb"] = ff.ParallelConfig(DeviceType.CPU, (1, 1), (0,))
    m = ff.FFModel(cfg)
    ids = m.create_tensor((16, 4), dtype="int32", name="ids")
    t = m.embedding(ids, 1000, 8, name="emb")
    t = m.dense(t, 4, name="head")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(m, lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers(seed=11)
    assert "emb" in m._host_embed
    rng = np.random.default_rng(0)
    X = rng.integers(0, 1000, (16, 4)).astype(np.int32)
    Y = (X[:, 0] % 4).astype(np.int32)[:, None]
    for _ in range(6):
        m.set_batch({ids: X}, Y)
        m.train_iteration()
    m.sync()
    w = m.get_parameter("emb", "weight")
    h = m.get_parameter("head", "kernel")
    ref = (float(np.sum(np.abs(w))), float(np.sum(w * w)),
           float(np.sum(np.abs(h))))
    np.testing.assert_allclose(fprints[0], ref, rtol=1e-4)


_CHILD_SPARSE_PIPE = """
import os, sys
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
sys.path.insert(0, {root!r})
import flexflow_tpu as ff
from flexflow_tpu.config import DeviceType
from flexflow_tpu.parallel import distributed as dist

dist.initialize()
pid = jax.process_index()

cfg = ff.FFConfig(batch_size=16, workers_per_node=4, num_nodes=2)
cfg.strategies['emb'] = ff.ParallelConfig(DeviceType.CPU, (1, 1), (0,))
m = ff.FFModel(cfg)
ids = m.create_tensor((16, 4), dtype='int32', name='ids')
t = m.embedding(ids, 1000, 8, name='emb')
t = m.dense(t, 24, activation='relu', name='fc1')
t = m.dense(t, 24, activation='relu', name='fc2')
t = m.dense(t, 4, name='fc3')
m.softmax(t, name='sm')
m.set_pipeline(num_stages=2, num_microbatches=4, dp_degree=2)
m.compile(ff.SGDOptimizer(m, lr=0.1),
          ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
          [ff.MetricsType.ACCURACY])
if m._pipeline_plan is None:
    print('PIPESKIP', pid, flush=True)
    dist.shutdown()
    sys.exit(0)
m.init_layers(seed=7)
assert 'emb' in m._host_embed, 'hetero head not taken'
assert [o.name for o in m._pipeline_plan['head']] == ['emb']

rng = np.random.default_rng(0)
X = rng.integers(0, 1000, (16, 4)).astype(np.int32)
Y = (X[:, 0] % 4).astype(np.int32)[:, None]
half = 8
lo, hi = pid * half, (pid + 1) * half
for _ in range(4):
    m.set_batch({{ids: X[lo:hi]}}, Y[lo:hi])
    m.train_iteration()
m.sync()
w = m.get_parameter('emb', 'weight')
h = m.get_parameter('fc3', 'kernel')
print('FPRINT', pid, float(np.sum(np.abs(w))), float(np.sum(w * w)),
      float(np.sum(np.abs(h))), flush=True)
dist.shutdown()
"""


@pytest.mark.slow
def test_two_process_hetero_head_pipeline(devices):
    """The full hetero composition at multi-process scale: row-sharded
    host tables (hetero head ahead of the ring) x GPipe over each
    host's local devices x dp over DCN — fingerprints agree across
    controllers AND match a single-process run of the same plan."""
    fprints, skipped = _run_two_controllers(_CHILD_SPARSE_PIPE)
    if skipped:
        pytest.skip("pipeline plan not expressible on the dcn x ici mesh")
    np.testing.assert_allclose(fprints[0], fprints[1], rtol=1e-5)

    import flexflow_tpu as ff
    from flexflow_tpu.config import DeviceType

    cfg = ff.FFConfig(batch_size=16, workers_per_node=8)
    cfg.strategies["emb"] = ff.ParallelConfig(DeviceType.CPU, (1, 1), (0,))
    m = ff.FFModel(cfg)
    ids = m.create_tensor((16, 4), dtype="int32", name="ids")
    t = m.embedding(ids, 1000, 8, name="emb")
    t = m.dense(t, 24, activation="relu", name="fc1")
    t = m.dense(t, 24, activation="relu", name="fc2")
    t = m.dense(t, 4, name="fc3")
    m.softmax(t, name="sm")
    m.set_pipeline(num_stages=2, num_microbatches=4, dp_degree=2)
    m.compile(ff.SGDOptimizer(m, lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers(seed=7)
    assert "emb" in m._host_embed
    rng = np.random.default_rng(0)
    X = rng.integers(0, 1000, (16, 4)).astype(np.int32)
    Y = (X[:, 0] % 4).astype(np.int32)[:, None]
    for _ in range(4):
        m.set_batch({ids: X}, Y)
        m.train_iteration()
    m.sync()
    w = m.get_parameter("emb", "weight")
    h = m.get_parameter("fc3", "kernel")
    ref = (float(np.sum(np.abs(w))), float(np.sum(w * w)),
           float(np.sum(np.abs(h))))
    np.testing.assert_allclose(fprints[0], ref, rtol=1e-4)
