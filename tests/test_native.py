"""Native (C++) component tests: event-sim engine parity with the Python
engine, and the multithreaded batch gather."""

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.config import ParallelConfig
from flexflow_tpu.simulator.cost_model import CostModel
from flexflow_tpu.simulator.machine import TPUMachineModel
from flexflow_tpu.simulator.simulator import Simulator
from flexflow_tpu.utils.native import data_lib, gather_rows, sim_lib, simulate_dag


def test_libs_build():
    assert sim_lib() is not None, "native simulator lib failed to build"
    assert data_lib() is not None, "native dataloader lib failed to build"


def test_simulate_dag_semantics():
    # chain on one device serializes; parallel branches overlap
    assert simulate_dag([1.0, 1.0], [0, 0], [], []) == 2.0
    assert simulate_dag([1.0, 1.0], [0, 1], [], []) == 1.0
    assert simulate_dag([1.0, 2.0, 3.0, 1.0], [0, 1, 2, 0],
                        [0, 0, 1, 2], [1, 2, 3, 3]) == 5.0
    with pytest.raises(RuntimeError):
        simulate_dag([1.0, 1.0], [0, 1], [0, 1], [1, 0])  # cycle


def test_native_matches_python_engine(devices):
    m = ff.FFModel(ff.FFConfig(batch_size=64))
    inp = m.create_tensor((64, 3, 16, 16))
    t = m.conv2d(inp, 8, 3, 3, 1, 1, 1, 1, name="c1")
    t = m.flat(t, name="f1")
    t = m.dense(t, 32, name="d1")
    m.softmax(t, name="s1")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy", ["accuracy"])
    mm = TPUMachineModel(num_devices=8)
    sim = Simulator(mm, CostModel(mm, measure=False))
    strategies = {op.name: ParallelConfig.data_parallel(op.output.num_dims, 8)
                  for op in m.ops}
    t_native = sim.simulate_runtime(m, strategies)
    # force the Python path
    sim._simulate_native = lambda tasks: None
    t_python = sim.simulate_runtime(m, strategies)
    assert t_native == pytest.approx(t_python, rel=1e-9)


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.standard_normal((100, 3, 8, 8), dtype=np.float32)
    idx = rng.integers(0, 100, 32)
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])
    # int dtype and 2-D rows
    src2 = rng.integers(0, 1000, (50, 7)).astype(np.int32)
    idx2 = rng.integers(0, 50, 17)
    np.testing.assert_array_equal(gather_rows(src2, idx2), src2[idx2])


def test_capi_smoke():
    """Build and run the C-API smoke test binary (reference analogue:
    tests/alexnet_c)."""
    import os
    import subprocess

    native = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native")
    subprocess.run(["make", "-C", native, "test_capi"], check=True,
                   capture_output=True, timeout=300)
    env = dict(os.environ)
    env["FLEXFLOW_TPU_PLATFORM"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(native) + ":" + env.get("PYTHONPATH", "")
    out = subprocess.run([os.path.join(native, "test_capi")], env=env,
                         capture_output=True, timeout=300, text=True)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "C API smoke test: OK" in out.stdout


def test_native_matches_python_engine_host_tier(devices):
    """ffsim parity must hold for the HOST device tier too (row-sparse
    tables: host timeline tasks + no-link host<->chip edges)."""
    m = ff.FFModel(ff.FFConfig(batch_size=32))
    ids = m.create_tensor((32, 2), dtype="int32", name="ids")
    t = m.embedding(ids, 10_000, 16, name="emb")
    t = m.dense(t, 8, name="head")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy",
              ["accuracy"])
    mm = TPUMachineModel(num_devices=8)
    sim = Simulator(mm, CostModel(mm, measure=False))
    strategies = {op.name: ParallelConfig.data_parallel(op.output.num_dims, 8)
                  for op in m.ops}
    strategies["emb"] = ParallelConfig.host_rowsparse()
    t_native = sim.simulate_runtime(m, strategies)
    sim._simulate_native = lambda tasks: None
    t_python = sim.simulate_runtime(m, strategies)
    assert t_native == pytest.approx(t_python, rel=1e-9)
