"""Chrome-trace export (flexflow_tpu/tools/timeline_export.py).

Well-formedness is the contract: Perfetto rejects a trace whose B/E
pairs don't match or nest, so the fold must stay stack-safe even when
producer clocks overlap (failover/hedge attempts).  The end-to-end test
drives a seeded 2-replica pool with FF_TRACE_SAMPLE=1 and asserts the
exported document carries a request track with prefill + decode child
spans under the attempt span — the acceptance shape from
docs/observability.md.
"""

import collections
import json

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.models.transformer import build_transformer
from flexflow_tpu.observability import events
from flexflow_tpu.serving.config import ServeConfig
from flexflow_tpu.serving.pool import ReplicaPool
from flexflow_tpu.tools import timeline_export
from flexflow_tpu.tools.trace_report import parse_trace

V = 32
MAX_SEQ = 64


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in ("FF_TELEMETRY", "FF_TELEMETRY_FILE", "FF_TRACE_SAMPLE",
                "FF_TRACE_CHUNK"):
        monkeypatch.delenv(var, raising=False)
    events.reset_active()
    yield
    events.reset_active()


@pytest.fixture(scope="module")
def model():
    cfg = ff.FFConfig(batch_size=4)
    m = ff.FFModel(cfg)
    build_transformer(m, 4, seq_length=MAX_SEQ, num_layers=1,
                      embed_dim=16, num_heads=2, vocab_size=V)
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=3)
    return m


def _check_wellformed(doc):
    """Perfetto's ground rules: monotone timestamps, every B matched by
    an E on the same track, named processes/threads."""
    evs = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    for a, b in zip(evs, evs[1:]):
        assert a["ts"] <= b["ts"], (a, b)
    depth = collections.Counter()
    for e in evs:
        key = (e["pid"], e["tid"])
        if e["ph"] == "B":
            depth[key] += 1
        elif e["ph"] == "E":
            depth[key] -= 1
            assert depth[key] >= 0, f"E without B on {key}"
    assert all(v == 0 for v in depth.values()), depth
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert any(m["name"] == "process_name" for m in metas)
    return evs


def _tracks(doc):
    """(process name, thread name) -> [events] from the metadata."""
    procs = {e["pid"]: e["args"]["name"]
             for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    threads = {(e["pid"], e["tid"]): e["args"]["name"]
               for e in doc["traceEvents"]
               if e["ph"] == "M" and e["name"] == "thread_name"}
    out = collections.defaultdict(list)
    for e in doc["traceEvents"]:
        if e["ph"] in ("B", "E", "i"):
            key = (procs[e["pid"]],
                   threads.get((e["pid"], e["tid"]), "?"))
            out[key].append(e)
    return out


# ---------------------------------------------------------------------------
# fold unit tests
# ---------------------------------------------------------------------------

def test_fold_clamps_overlap_to_matched_pairs():
    # child claims to outlive its parent (overlapping producer clocks):
    # the fold must clamp, never emit unmatched/crossing pairs
    spans = [(0, 100, "parent", {}), (50, 100, "child", {})]
    out = timeline_export._fold_spans(spans, pid=1, tid=1)
    assert [e["ph"] for e in out] == ["B", "B", "E", "E"]
    # the child's E lands at the parent's end, not past it
    assert out[2]["ts"] == 100 and out[3]["ts"] == 100


def test_fold_sequential_spans_close_in_order():
    spans = [(0, 10, "a", {}), (20, 10, "b", {})]
    out = timeline_export._fold_spans(spans, pid=1, tid=1)
    assert [(e["ph"], e.get("name")) for e in out] == [
        ("B", "a"), ("E", None), ("B", "b"), ("E", None)]


def test_sampled_traces_needs_span_ids():
    recs = [
        {"t": "span", "name": "step", "ts": 0.0, "dur": 1.0,
         "attrs": {"trace_id": "run" * 8}},          # run-level stamp
        {"t": "span", "name": "serve_prefill", "ts": 0.0, "dur": 0.1,
         "attrs": {"trace_id": "aa" * 16}},          # unsampled request
        {"t": "span", "name": "serve_attempt", "ts": 0.0, "dur": 0.2,
         "attrs": {"trace_id": "bb" * 16, "span_id": "cc" * 8}},
    ]
    assert timeline_export.sampled_traces(recs) == {"bb" * 16}


def test_export_synthetic_track_layout(tmp_path):
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    log.span_at("step", 0.0, 0.5, step=0, trace_id="run" * 8)
    log.span_at("mcmc_search", 0.0, 0.2, budget=10)
    log.event("compile_done", op="all")
    log.event("chip_probe", ok=True)
    log.gauge("serve_batch_occupancy", 1.5, replica="replica-0")
    log.gauge("mfu", 0.3)
    log.close()
    doc = timeline_export.export_records(parse_trace(log.path))
    _check_wellformed(doc)
    tracks = _tracks(doc)
    assert ("training", "train") in tracks    # run-trace stays here
    assert ("search", "search") in tracks
    assert [e["name"] for e in tracks[("compile", "compile")]] \
        == ["compile_done"]
    assert [e["name"] for e in tracks[("chips", "chips")]] \
        == ["chip_probe"]
    counters = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert {e["name"] for e in counters} \
        == {"occupancy replica-0", "mfu"}
    assert doc["otherData"]["request_tracks"] == []


# ---------------------------------------------------------------------------
# end to end: seeded 2-replica run -> Perfetto-loadable timeline
# ---------------------------------------------------------------------------

def test_two_replica_run_exports_request_tracks(model, tmp_path,
                                               monkeypatch):
    monkeypatch.setenv("FF_TRACE_SAMPLE", "1")
    monkeypatch.setenv("FF_TRACE_CHUNK", "4")
    log = events.EventLog(str(tmp_path / "serve.jsonl"))
    cfg = ServeConfig(max_batch=2, max_seq=MAX_SEQ, replicas=2,
                      replica_timeout_s=120.0)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, V, size=int(rng.integers(3, 12)))
               .astype(np.int32) for _ in range(6)]
    with ReplicaPool(model, config=cfg, telemetry=log) as pool:
        handles = [pool.submit(p, 8) for p in prompts]
        for h in handles:
            h.result(120)
    log.close()

    # CLI round trip: the written file is plain Chrome-trace JSON
    out = str(tmp_path / "timeline.json")
    assert timeline_export.main([log.path, "-o", out]) == 0
    with open(out) as f:
        doc = json.load(f)
    assert doc["displayTimeUnit"] == "ms"
    _check_wellformed(doc)

    # one request track per trace root + one per attempt
    req_tracks = doc["otherData"]["request_tracks"]
    assert len(req_tracks) >= 6
    tracks = _tracks(doc)
    attempt_tracks = [k for k in tracks
                      if k[0] == "requests" and "/a" in k[1]]
    assert len(attempt_tracks) >= 6
    # every attempt track nests prefill + decode inside the attempt span
    for key in attempt_tracks:
        begins = [e["name"] for e in tracks[key] if e["ph"] == "B"]
        assert begins[0] == "serve_attempt", begins
        assert "serve_prefill" in begins and "serve_decode" in begins
    # root tracks carry the client-level span
    root_tracks = [k for k in tracks
                   if k[0] == "requests" and "/" not in k[1]]
    for key in root_tracks:
        assert [e["name"] for e in tracks[key] if e["ph"] == "B"] \
            == ["serve_request"]
    # replica gauges became counter tracks on the serving process
    counters = {e["name"] for e in doc["traceEvents"] if e["ph"] == "C"}
    assert any(c.startswith("occupancy replica-") for c in counters)


def test_unsampled_run_has_no_request_tracks(model, tmp_path,
                                             monkeypatch):
    monkeypatch.setenv("FF_TRACE_SAMPLE", "0")
    log = events.EventLog(str(tmp_path / "serve.jsonl"))
    cfg = ServeConfig(max_batch=2, max_seq=MAX_SEQ, replicas=2,
                      replica_timeout_s=120.0)
    p = np.arange(5, dtype=np.int32)
    with ReplicaPool(model, config=cfg, telemetry=log) as pool:
        pool.submit(p, 4).result(120)
    log.close()
    doc = timeline_export.export_records(parse_trace(log.path))
    _check_wellformed(doc)
    assert doc["otherData"]["request_tracks"] == []
    # the serve spans still render — on the serving process instead
    tracks = _tracks(doc)
    serving = [k for k in tracks if k[0] == "serving"]
    names = {e["name"] for k in serving for e in tracks[k]
             if e["ph"] == "B"}
    assert "serve_prefill" in names and "serve_decode" in names


def test_cli_empty_trace_fails_loud(tmp_path, capsys):
    p = tmp_path / "empty.jsonl"
    p.write_text("")
    assert timeline_export.main([str(p)]) == 1
    assert "no records" in capsys.readouterr().err
