"""Pipeline stage assignment as part of the searched space
(simulator/pipeline_search.py; round-2 VERDICT weak #3: "the search
cannot discover pipelining of real graphs").
"""

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.simulator.machine import TPUMachineModel
from flexflow_tpu.simulator.pipeline_search import (cost_pipeline_plan,
                                                    search_pipeline,
                                                    suggest_parallelization)


def _mlp(batch=32, width=64, depth=6):
    cfg = ff.FFConfig(batch_size=batch, workers_per_node=8)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, width), nchw=False)
    t = inp
    for i in range(depth):
        t = m.dense(t, width, activation="relu", name=f"fc{i}")
    t = m.dense(t, 10, name="head")
    m.softmax(t, name="sm")
    return m


def test_search_pipeline_returns_executable_plan(devices):
    m = _mlp()
    plan = search_pipeline(m, machine_model=TPUMachineModel(num_devices=8))
    assert plan is not None
    S, dp = plan["num_stages"], plan["dp_degree"]
    assert S * dp == 8 and S >= 2
    # the plan actually runs through set_pipeline on the real mesh
    m2 = _mlp()
    m2.set_pipeline(num_stages=S, dp_degree=dp,
                    num_microbatches=plan["num_microbatches"])
    m2.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
               ["accuracy"])
    m2.init_layers(seed=1)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 64), dtype=np.float32)
    y = rng.integers(0, 10, size=(32, 1), dtype=np.int32)
    m2.set_batch({m2.input_tensors[0]: x}, y)
    m2.train_iteration()
    m2.sync()
    assert m2._pipeline_plan is not None


def test_search_sweeps_m_and_prices_remat(devices):
    """The default sweep covers every divisor-M of the local batch and
    both schedules; larger M shrinks the bubble fraction, and the remat
    variant pays a recompute forward but stashes only boundary carries
    (ADR-002)."""
    m = _mlp()
    mm = TPUMachineModel(num_devices=8)
    from flexflow_tpu.simulator.cost_model import CostModel

    cost = CostModel(mm, measure=False)
    r_small = cost_pipeline_plan(m, mm, cost, S=4, dp=2, microbatches=2)
    r_big = cost_pipeline_plan(m, mm, cost, S=4, dp=2, microbatches=16)
    assert r_small and r_big
    # bigger M amortizes the fill/drain bubble per sample
    assert r_big["t"] / 16 < r_small["t"] / 2
    r_rm = cost_pipeline_plan(m, mm, cost, S=4, dp=2, microbatches=16,
                              remat=True)
    assert r_rm is not None
    assert r_rm["t"] > r_big["t"]      # recompute forward is priced
    assert r_rm["mem"] < r_big["mem"]  # boundary-only residuals
    plan = search_pipeline(m, machine_model=mm)
    assert plan is not None and "remat" in plan and plan["mem_bytes"] > 0
    # the sweep reached past the legacy {4, 8} grid
    assert plan["num_microbatches"] in range(1, 17)


def test_search_rejects_over_memory_plans(devices):
    """A machine with a tiny HBM forces the search toward remat or
    rejects the plan outright — memory is part of the objective."""
    m = _mlp()
    from flexflow_tpu.simulator.cost_model import CostModel

    mm_small = TPUMachineModel(num_devices=8, hbm_capacity=1.2e5)
    cost = CostModel(mm_small, measure=False)
    r = cost_pipeline_plan(m, mm_small, cost, S=4, dp=2, microbatches=16,
                           remat=False)
    # non-remat residuals blow the 120 KB budget; remat still fits, and
    # the default best-of-both costing therefore lands on remat
    assert r is None
    r_any = cost_pipeline_plan(m, mm_small, cost, S=4, dp=2,
                               microbatches=16)
    assert r_any is not None and r_any["remat"] is True
    assert r_any["mem"] <= 0.9 * 1.2e5


def test_pipeline_cost_scales_with_stages(devices):
    """More slots shrink per-slot compute; the bubble term (M+S-1) and
    comm keep the curve honest — cost must be finite and positive, and
    the single-microbatch degenerate case must price the full bubble."""
    m = _mlp()
    mm = TPUMachineModel(num_devices=8)
    from flexflow_tpu.simulator.cost_model import CostModel

    cost = CostModel(mm, measure=False)
    t2 = cost_pipeline_plan(m, mm, cost, S=2, dp=4, microbatches=4)
    t4 = cost_pipeline_plan(m, mm, cost, S=4, dp=2, microbatches=4)
    assert t2 and t4 and t2["t"] > 0 and t4["t"] > 0 and t2["t"] != t4["t"]
    # a requested M that doesn't divide the local batch is ADJUSTED and
    # the adjusted value is what the plan reports
    t4_m3 = cost_pipeline_plan(m, mm, cost, S=4, dp=2, microbatches=3)
    assert t4_m3 is not None and (32 // 2) % t4_m3["m"] == 0
    # an inexecutable plan (more stages than segment ops: 7 here —
    # softmax is outside) prices as None
    assert cost_pipeline_plan(m, mm, cost, S=8, dp=1, microbatches=4) is None


def test_branching_graph_prices(devices):
    """Branching graphs (multi-input concat crossing stages) price with
    the generalized k-tensor-hop planner — the pipeline search covers
    them instead of reporting n/a (reference pipelines arbitrary per-op
    placements, nmt/nmt.cc:269-308)."""
    from flexflow_tpu.simulator.cost_model import CostModel

    cfg = ff.FFConfig(batch_size=16, workers_per_node=8)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 16), nchw=False)
    a = m.dense(inp, 16, name="t1")
    b = m.dense(inp, 16, name="t2")     # second branch off the input
    t = m.concat([a, b], axis=1, name="cc")
    t = m.dense(t, 8, name="head")
    m.softmax(t, name="sm")
    mm = TPUMachineModel(num_devices=8)
    cost = CostModel(mm, measure=False)
    r = cost_pipeline_plan(m, mm, cost, S=2, dp=4, microbatches=4)
    assert r is not None and np.isfinite(r["t"]) and r["t"] > 0
    plan = search_pipeline(m, machine_model=mm)
    assert plan is not None and plan["num_stages"] >= 2


def test_suggest_covers_both_spaces(devices):
    """The suggestion reports both searched spaces and picks the min."""
    m = _mlp()
    out = suggest_parallelization(m, budget=300,
                                  machine_model=TPUMachineModel(num_devices=8))
    alts = out["alternatives"]
    assert alts["dims_s"] is not None and alts["dims_s"] > 0
    assert out["kind"] in ("dims", "pipeline")
    if out["kind"] == "pipeline":
        assert out["simulated_s"] == alts["pipeline_s"] <= alts["dims_s"]
        assert out["pipeline"]["num_stages"] >= 2
    else:
        assert "strategies" in out and out["simulated_s"] == alts["dims_s"]


def test_compile_applies_searched_pipeline(devices):
    """--search-pipeline: compile() adopts the pipeline plan when it
    beats the dim strategy, and one train step runs under it."""
    cfg = ff.FFConfig(batch_size=32, workers_per_node=8, search_budget=200,
                      search_pipeline=True)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((32, 64), nchw=False)
    t = inp
    for i in range(6):
        t = m.dense(t, 64, activation="relu", name=f"fc{i}")
    t = m.dense(t, 10, name="head")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=1)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 64), dtype=np.float32)
    y = rng.integers(0, 10, size=(32, 1), dtype=np.int32)
    m.set_batch({inp: x}, y)
    m.train_iteration()
    m.sync()
    # either the search adopted a pipeline plan (and it executed), or it
    # measurably preferred the dim strategy — both must leave a runnable
    # model; assert the pipeline path at least when adopted
    if m._pipeline_plan is not None:
        assert m._pipe_pack() is not None
