"""Top-level application drivers as integration tests (reference: the
cpp example apps ARE the test suite, SURVEY §4.1) — each runs its real
CLI entry at a reduced size."""

import sys

import pytest

sys.path.insert(0, ".")


@pytest.mark.slow
def test_nmt_driver():
    from examples.nmt import main

    main(["-b", "8", "--seq", "6", "--hidden", "32", "--embed", "32",
          "--vocab", "64", "--layers", "1", "--iters", "2", "--translate"])


def test_dlrm_driver():
    from examples.dlrm import main

    main(["-b", "16", "--arch-embedding-size", "64-64",
          "--arch-sparse-feature-size", "16",
          "--arch-mlp-bot", "8-16", "--arch-mlp-top", "32-16-1",
          "--epochs", "1"])


@pytest.mark.slow
def test_pca_driver():
    from examples.pca import main

    main(["-b", "16"])


@pytest.mark.slow
def test_candle_uno_driver():
    from examples.candle_uno import main

    main(["-b", "8", "--epochs", "1"])


def test_transformer_generate_example():
    from examples.transformer_generate import top_level_task

    assert top_level_task(argv=[], iterations=120) >= 90.0
