"""SOAP strategy lowering + cross-strategy numerical equivalence.

The reference's core magic is per-op hybrid parallelization with implicit
resharding between differently-partitioned ops (SURVEY.md §2.3, §7).  On
the 8-device virtual mesh these tests check:
  * ParallelConfig → PartitionSpec lowering (mesh axes factoring),
  * weights are actually sharded on device (tensor parallel dense),
  * a training run under ANY strategy (DP / TP / spatial / hybrid) yields
    numerically equivalent results to single-device execution — the
    analogue of the reference's "strategy changes placement, not math"
    contract.
"""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec

import flexflow_tpu as ff
from flexflow_tpu.parallel.mesh import Machine


def test_mesh_factoring(devices):
    mach = Machine(devices)
    assert mach.num_devices == 8
    assert sorted(mach.axis_sizes) == [2, 2, 2]
    spec = mach.spec_for_config(ff.ParallelConfig(dims=(4, 1, 2, 1)))
    assert spec == PartitionSpec(("m0", "m1"), None, "m2")
    spec2 = mach.spec_for_config(ff.ParallelConfig(dims=(8, 1)))
    assert spec2 == PartitionSpec(("m0", "m1", "m2"))
    spec3 = mach.spec_for_config(ff.ParallelConfig(dims=(1, 1)))
    assert spec3 == PartitionSpec()
    with pytest.raises(ValueError):
        mach.axes_for_degrees([3])


def build_and_train(strategies, batch=16, steps=6, seed=3):
    cfg = ff.FFConfig(batch_size=batch, strategies=dict(strategies))
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 3, 12, 12))
    t = m.conv2d(inp, 8, 3, 3, 1, 1, 1, 1, activation=ff.ActiMode.RELU, name="conv1")
    t = m.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")
    t = m.flat(t, name="flat1")
    t = m.dense(t, 32, activation=ff.ActiMode.RELU, name="fc1")
    t = m.dense(t, 10, name="fc2")
    t = m.softmax(t, name="softmax1")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy",
              ["accuracy", "sparse_categorical_crossentropy"])
    m.init_layers(seed=seed)

    rng = np.random.default_rng(7)
    x = rng.standard_normal((batch * 2, 3, 12, 12), dtype=np.float32)
    y = rng.integers(0, 10, size=(batch * 2, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    losses = []
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()
    m._drain_metrics()
    fc2 = m.get_parameter("fc2", "kernel")
    conv1 = m.get_parameter("conv1", "kernel")
    return fc2, conv1, m


DP8 = {
    "conv1": ff.ParallelConfig(dims=(8, 1, 1, 1)),
    "pool1": ff.ParallelConfig(dims=(8, 1, 1, 1)),
    "flat1": ff.ParallelConfig(dims=(8, 1)),
    "fc1": ff.ParallelConfig(dims=(8, 1)),
    "fc2": ff.ParallelConfig(dims=(8, 1)),
    "softmax1": ff.ParallelConfig(dims=(8, 1)),
}

# Hybrid SOAP: conv spatially split (sample×height), dense tensor-parallel.
HYBRID = {
    "conv1": ff.ParallelConfig(dims=(2, 2, 2, 1)),
    "pool1": ff.ParallelConfig(dims=(2, 2, 1, 1)),
    "flat1": ff.ParallelConfig(dims=(2, 1)),
    "fc1": ff.ParallelConfig(dims=(2, 4)),   # tensor parallel over out dim
    "fc2": ff.ParallelConfig(dims=(2, 1)),
    "softmax1": ff.ParallelConfig(dims=(2, 1)),
}

SINGLE = {
    name: ff.ParallelConfig(dims=(1,) * nd)
    for name, nd in [("conv1", 4), ("pool1", 4), ("flat1", 2),
                     ("fc1", 2), ("fc2", 2), ("softmax1", 2)]
}


def test_tensor_parallel_dense_is_sharded(devices):
    _, _, m = build_and_train(HYBRID, steps=1)
    k = m._params["fc1"]["kernel"]
    # out-dim split 4 ways → each device holds a (in, out/4) shard
    shard_shape = k.sharding.shard_shape(k.shape)
    assert shard_shape[1] == k.shape[1] // 4


@pytest.mark.parametrize("strategy", [DP8, HYBRID], ids=["dp8", "hybrid"])
def test_strategy_equivalence(devices, strategy):
    """Any SOAP strategy must compute the same training trajectory as
    single-device execution (up to float reassociation)."""
    fc2_a, conv_a, _ = build_and_train(SINGLE)
    fc2_b, conv_b, _ = build_and_train(strategy)
    np.testing.assert_allclose(fc2_a, fc2_b, rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(conv_a, conv_b, rtol=5e-4, atol=5e-5)


def test_import_export_strategy_file(devices, tmp_path):
    path = str(tmp_path / "st.pb")
    ff.save_strategies_to_file(path, HYBRID)
    cfg = ff.FFConfig(batch_size=16, import_strategy_file=path)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 3, 12, 12))
    t = m.conv2d(inp, 8, 3, 3, 1, 1, 1, 1, name="conv1")
    t = m.flat(t, name="flat1")
    t = m.dense(t, 32, name="fc1")
    m.softmax(t, name="softmax1")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy", ["accuracy"])
    assert m.ops[0].pc.dims == (2, 2, 2, 1)
    assert m.ops[2].pc.dims == (2, 4)
    # a degree that does not divide the dim is legalized down (10 % 4 != 0)
    m2 = ff.FFModel(ff.FFConfig(batch_size=16, import_strategy_file=path))
    inp2 = m2.create_tensor((16, 48), nchw=False)
    t2 = m2.dense(inp2, 10, name="fc1")
    m2.softmax(t2, name="softmax1")
    m2.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy", ["accuracy"])
    assert m2.ops[0].pc.dims == (2, 2)


def test_rank_mismatched_strategy_degrades_to_dp(devices):
    """find_parallel_config with a wrong-rank entry falls back to data
    parallelism instead of asserting (reference: strategy.cc:28-85
    asserts; we degrade — SURVEY §2.1 mapper semantics)."""
    cfg = ff.FFConfig(batch_size=16, workers_per_node=8)
    # a 4-D conv-style config attached to a 2-D dense op: wrong rank
    cfg.strategies["fc1"] = ff.ParallelConfig(dims=(2, 2, 1, 1))
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False)
    t = m.dense(inp, 16, activation="relu", name="fc1")
    t = m.dense(t, 4, name="fc2")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy",
              ["accuracy"])
    fc1 = next(op for op in m.ops if op.name == "fc1")
    assert fc1.pc.ndims == 2           # degraded to the op's rank
    assert fc1.pc.dims[0] == 8         # full data parallelism
    m.init_layers(seed=0)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 8), dtype=np.float32)
    y = rng.integers(0, 4, size=(16, 1), dtype=np.int32)
    m.set_batch({inp: x}, y)
    m.train_iteration()
    m.sync()
