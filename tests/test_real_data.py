"""Real-dataset train-to-threshold verification.

The reference's de-facto learning contract is ``python/test.sh``
training real MNIST/CIFAR/Reuters to accuracy thresholds
(reference: examples/python/keras/accuracy.py).  This environment has
zero egress, so the canonical archives are unobtainable and the keras
loaders LOUDLY substitute synthetic data (see
keras/utils/data_utils.warn_synthetic).  scikit-learn however ships the
REAL UCI handwritten-digits dataset inside the package (1797 genuine
8x8 grayscale digit scans) — training on it proves the framework learns
real data, not just the synthetic fixtures' planted patterns.
"""

import numpy as np
import pytest

import flexflow_tpu as ff

sklearn_datasets = pytest.importorskip("sklearn.datasets")


def test_trains_real_digits_to_threshold(devices):
    digits = sklearn_datasets.load_digits()
    x = (digits.images / 16.0).astype(np.float32).reshape(-1, 64)
    y = digits.target.astype(np.int32).reshape(-1, 1)
    n_train = 1536  # 12 batches of 128; the rest is the eval split
    x_train, y_train = x[:n_train], y[:n_train]
    x_test, y_test = x[n_train:], y[n_train:]

    cfg = ff.FFConfig(batch_size=128, seed=7)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((128, 64), name="pix", nchw=False)
    t = m.dense(inp, 64, activation="relu", name="fc1")
    t = m.dense(t, 10, name="fc2")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(m, lr=0.5),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers(seed=7)

    dl = ff.DataLoader(m, {inp: x_train}, y_train)
    for _ in range(15):  # epochs
        for _ in range(n_train // 128):
            dl.next_batch(m)
            m.train_iteration()
    m.sync()

    # held-out REAL digits: well above the 10-class 10% chance line
    correct = total = 0
    for i in range(len(x_test) // 128):
        xb = x_test[i * 128:(i + 1) * 128]
        yb = y_test[i * 128:(i + 1) * 128]
        m.set_batch({inp: xb}, yb)
        pred = np.argmax(m.predict_batch(), axis=-1)
        correct += int((pred == yb[:, 0]).sum())
        total += len(xb)
    acc = correct / total
    assert acc >= 0.85, f"held-out accuracy {acc:.3f} < 0.85 on real digits"
