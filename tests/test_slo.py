"""SLO burn-rate evaluation (flexflow_tpu/observability/slo.py).

Burn rates are checked against hand-computed windows (the evaluator's
clock is the record timestamp, so the arithmetic is exact), alerting is
checked for hysteresis (one firing per episode, cleared only at half
the threshold), and the metrics wiring is checked end to end: a
serve_request_done stream through a real EventLog must surface as
``ff_slo_burn_rate{slo,window}`` in a Prometheus scrape.
"""

import urllib.request

import pytest

from flexflow_tpu.observability import events, metrics, slo


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    for var in ("FF_TELEMETRY", "FF_TELEMETRY_FILE", "FF_METRICS_PORT",
                "FF_METRICS_HOST", "FF_SLO_TTFT_MS", "FF_SLO_TPOT_MS",
                "FF_SLO_QUEUE_WAIT_MS", "FF_SLO_AVAILABILITY",
                "FF_SLO_OBJECTIVE", "FF_SLO_WINDOWS",
                "FF_SLO_BURN_ALERT"):
        monkeypatch.delenv(var, raising=False)
    events.reset_active()
    metrics.stop()      # also resets slo's attach list
    yield
    metrics.stop()
    events.reset_active()


class _FakeLog:
    """Capture the evaluator's emissions without a real sink."""

    def __init__(self):
        self.gauges = []    # (name, value, attrs)
        self.events = []    # (name, attrs)

    def gauge(self, name, v, **attrs):
        self.gauges.append((name, v, attrs))

    def event(self, name, **attrs):
        self.events.append((name, attrs))

    def add_observer(self, fn):
        pass


def _done(ts, **attrs):
    attrs.setdefault("status", "done")
    return {"t": "event", "name": "serve_request_done", "ts": ts,
            "attrs": attrs}


# ---------------------------------------------------------------------------
# burn-rate arithmetic vs hand-computed windows
# ---------------------------------------------------------------------------

def test_burn_rate_matches_hand_computation():
    log = _FakeLog()
    target = slo.SLOTarget("ttft", "ttft_s", 0.1, objective=0.9)
    ev = slo.BurnRateEvaluator(log, targets=[target], windows=(2.0, 4.0),
                               burn_alert=100.0)   # alerts out of the way
    for ts, ttft in ((0.0, 0.05), (1.0, 0.2), (2.0, 0.05), (3.0, 0.05)):
        ev.observe(_done(ts, ttft_s=ttft))
    last = {(a["slo"], a["window"]): v
            for n, v, a in log.gauges if n == "slo_burn_rate"}
    # at ts=3, window 2 covers ts in [1, 3]: bad 1/3 -> /(1-0.9) = 3.3333
    assert last[("ttft", "2")] == pytest.approx(3.3333, abs=1e-4)
    # window 4 covers all four: bad 1/4 -> 2.5
    assert last[("ttft", "4")] == pytest.approx(2.5)
    # budget over the LONG window: 1 - 2.5, floored at 0
    budget = [v for n, v, a in log.gauges
              if n == "slo_budget_remaining" and a["slo"] == "ttft"]
    assert budget[-1] == 0.0
    # a request with the latency field missing (shed/timeout) is BAD
    ev.observe(_done(3.5, status="timeout"))
    last = {(a["slo"], a["window"]): v
            for n, v, a in log.gauges if n == "slo_burn_rate"}
    # window 2 now covers ts in [1.5, 3.5]: bads = missing-field one -> 1/3
    assert last[("ttft", "2")] == pytest.approx(3.3333, abs=1e-4)


def test_availability_counts_status():
    log = _FakeLog()
    target = slo.SLOTarget("availability", None, None, objective=0.5)
    ev = slo.BurnRateEvaluator(log, targets=[target], windows=(10.0,),
                               burn_alert=100.0)
    for ts, st in ((0.0, "done"), (1.0, "error"), (2.0, "done"),
                   (3.0, "done")):
        ev.observe(_done(ts, status=st))
    last = [v for n, v, a in log.gauges if n == "slo_burn_rate"][-1]
    # bad 1/4 over (1 - 0.5) -> 0.5
    assert last == pytest.approx(0.5)


def test_samples_evicted_past_longest_window():
    log = _FakeLog()
    target = slo.SLOTarget("availability", None, None, objective=0.9)
    ev = slo.BurnRateEvaluator(log, targets=[target], windows=(2.0, 4.0),
                               burn_alert=100.0)
    ev.observe(_done(0.0, status="error"))
    for ts in (5.0, 6.0, 7.0):
        ev.observe(_done(ts))
    # the ts=0 failure fell out of even the long window -> burn 0
    last = {a["window"]: v
            for n, v, a in log.gauges if n == "slo_burn_rate"}
    assert last["2"] == 0.0 and last["4"] == 0.0
    assert len(ev._samples) == 3


# ---------------------------------------------------------------------------
# alert hysteresis: one firing per episode, clear at half threshold
# ---------------------------------------------------------------------------

def test_alert_fires_once_and_clears_with_hysteresis():
    log = _FakeLog()
    target = slo.SLOTarget("availability", None, None, objective=0.9)
    ev = slo.BurnRateEvaluator(log, targets=[target], windows=(2.0, 4.0),
                               burn_alert=2.0)
    for ts in range(5):                       # sustained outage
        ev.observe(_done(float(ts), status="error"))
    firing = [a for n, a in log.events if n == "slo_alert"]
    assert len(firing) == 1, "alert must fire once per episode"
    assert firing[0]["state"] == "firing"
    assert firing[0]["slo"] == "availability"
    assert firing[0]["burn_2s"] == pytest.approx(10.0)
    for ts in range(5, 21):                   # recovery
        ev.observe(_done(float(ts)))
    states = [a["state"] for n, a in log.events if n == "slo_alert"]
    assert states == ["firing", "cleared"]
    # cleared only once burn < threshold/2 on EVERY window — while the
    # long window still held a failure the alert stayed up
    cleared = [a for n, a in log.events if a["state"] == "cleared"][0]
    assert cleared["burn_2s"] < 1.0 and cleared["burn_4s"] < 1.0


def test_alert_needs_all_windows():
    # a 1-sample blip drives the SHORT window way up but not the long
    # one -> no alert (the multi-window guard)
    log = _FakeLog()
    target = slo.SLOTarget("availability", None, None, objective=0.9)
    ev = slo.BurnRateEvaluator(log, targets=[target], windows=(1.0, 60.0),
                               burn_alert=2.0)
    for ts in range(50):
        ev.observe(_done(float(ts)))
    ev.observe(_done(50.0, status="error"))   # short window: burn 10
    assert [n for n, _ in log.events if n == "slo_alert"] == []


# ---------------------------------------------------------------------------
# env parsing (loud) + declarative defaults
# ---------------------------------------------------------------------------

def test_targets_from_env_defaults_and_disable(monkeypatch):
    names = [t.name for t in slo.targets_from_env()]
    assert names == ["ttft", "tpot", "queue_wait", "availability"]
    monkeypatch.setenv("FF_SLO_TTFT_MS", "0")
    monkeypatch.setenv("FF_SLO_AVAILABILITY", "0")
    names = [t.name for t in slo.targets_from_env()]
    assert names == ["tpot", "queue_wait"]
    monkeypatch.setenv("FF_SLO_TPOT_MS", "250")
    tpot = slo.targets_from_env()[0]
    assert tpot.threshold_s == pytest.approx(0.25)


def test_env_parsing_is_loud(monkeypatch):
    monkeypatch.setenv("FF_SLO_TTFT_MS", "fast")
    with pytest.raises(ValueError, match="FF_SLO_TTFT_MS"):
        slo.targets_from_env()
    monkeypatch.delenv("FF_SLO_TTFT_MS")
    monkeypatch.setenv("FF_SLO_OBJECTIVE", "1.5")
    with pytest.raises(ValueError, match="FF_SLO_OBJECTIVE"):
        slo.targets_from_env()
    monkeypatch.delenv("FF_SLO_OBJECTIVE")
    monkeypatch.setenv("FF_SLO_WINDOWS", "60,banana")
    with pytest.raises(ValueError, match="FF_SLO_WINDOWS"):
        slo.windows_from_env()
    monkeypatch.setenv("FF_SLO_WINDOWS", "-5")
    with pytest.raises(ValueError, match="positive"):
        slo.windows_from_env()
    monkeypatch.setenv("FF_SLO_WINDOWS", "300,60")
    assert slo.windows_from_env() == (60.0, 300.0)   # sorted


# ---------------------------------------------------------------------------
# wiring: maybe_attach + the metrics plane
# ---------------------------------------------------------------------------

def test_maybe_attach_gates_and_idempotence(tmp_path, monkeypatch):
    assert slo.maybe_attach(None) is None          # telemetry off
    for var in ("FF_SLO_TTFT_MS", "FF_SLO_TPOT_MS",
                "FF_SLO_QUEUE_WAIT_MS", "FF_SLO_AVAILABILITY"):
        monkeypatch.setenv(var, "0")
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    assert slo.maybe_attach(log) is None           # every SLO disabled
    for var in ("FF_SLO_TTFT_MS", "FF_SLO_TPOT_MS",
                "FF_SLO_QUEUE_WAIT_MS", "FF_SLO_AVAILABILITY"):
        monkeypatch.delenv(var)
    ev = slo.maybe_attach(log)
    assert ev is not None
    assert slo.maybe_attach(log) is ev             # idempotent per log
    assert len(log._observers) == 1
    log.close()


def test_scrape_carries_slo_series(tmp_path, monkeypatch):
    monkeypatch.setenv("FF_METRICS_PORT", "0")
    monkeypatch.setenv("FF_METRICS_HOST", "127.0.0.1")
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    reg = metrics.maybe_start(log)
    assert reg is not None
    # a flash crowd: every request blows the TTFT target
    for _ in range(6):
        log.event("serve_request_done", status="done", ttft_s=9.0,
                  tpot_s=0.001, queue_wait_s=0.001)
    port = metrics.server_port()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        text = r.read().decode()
    assert 'ff_slo_burn_rate{slo="ttft",window="60"}' in text
    assert 'ff_slo_budget_remaining{slo="ttft"}' in text
    # ttft burn is pinned at 100x (all bad, objective 0.99)
    line = [l for l in text.splitlines()
            if l.startswith('ff_slo_burn_rate{slo="ttft",window="60"}')][0]
    assert float(line.split()[-1]) == pytest.approx(100.0)
    # the healthy SLOs burn 0 and the alert fired for ttft only
    line = [l for l in text.splitlines()
            if l.startswith('ff_slo_burn_rate{slo="tpot",window="60"}')][0]
    assert float(line.split()[-1]) == 0.0
    assert 'ff_events_total{event="slo_alert"} 1' in text
    log.close()
