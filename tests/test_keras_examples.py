"""Keras example scripts as integration tests (reference: python/test.sh
runs every keras example; accuracy asserted by VerifyMetrics inside each
script — SURVEY.md §4.1)."""

import sys

import pytest

sys.path.insert(0, ".")


def test_seq_mnist_mlp():
    from examples.keras.seq_mnist_mlp import top_level_task

    top_level_task(num_samples=512, epochs=2)


@pytest.mark.slow
def test_seq_mnist_cnn():
    from examples.keras.seq_mnist_cnn import top_level_task

    top_level_task(num_samples=512, epochs=4)


def test_func_mnist_mlp_concat():
    from examples.keras.func_mnist_mlp_concat import top_level_task

    top_level_task(num_samples=1024, epochs=6)


@pytest.mark.slow
def test_seq_reuters_mlp():
    from examples.keras.seq_reuters_mlp import top_level_task

    top_level_task(num_samples=1024, epochs=8)


@pytest.mark.slow
def test_seq_cifar10_cnn():
    from examples.keras.seq_cifar10_cnn import top_level_task

    top_level_task(num_samples=512, epochs=4)


def test_net2net_weight_transfer():
    from examples.keras.seq_mnist_cnn_net2net import top_level_task

    top_level_task(num_samples=512, epochs=4)


@pytest.mark.slow
def test_candle_uno_builds_and_trains():
    import numpy as np

    import flexflow_tpu as ff
    from flexflow_tpu.models.candle_uno import build_candle_uno
    from examples.candle_uno import synthetic_batch

    # Scaled-down towers for test speed; same topology.
    feature_shapes = {"dose": 1, "cell.rnaseq": 64,
                      "drug.descriptors": 128, "drug.fingerprints": 96}
    input_features = {"dose1": "dose", "dose2": "dose",
                      "cell.rnaseq": "cell.rnaseq",
                      "drug1.descriptors": "drug.descriptors",
                      "drug1.fingerprints": "drug.fingerprints"}
    cfg = ff.FFConfig(batch_size=16)
    model = ff.FFModel(cfg)
    inputs, _ = build_candle_uno(model, 16, dense_layers=[32] * 3,
                                 dense_feature_layers=[32] * 3,
                                 input_features=input_features,
                                 feature_shapes=feature_shapes)
    model.compile(ff.SGDOptimizer(model, lr=0.01),
                  ff.LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                  [ff.MetricsType.MEAN_SQUARED_ERROR])
    model.init_layers()
    xs, labels = synthetic_batch(16, input_features, feature_shapes)
    model.set_batch({inputs[k]: v for k, v in xs.items()}, labels)
    losses = []
    for _ in range(20):
        model.train_iteration()
        pm = model.get_metrics()
        losses.append(pm.mse_loss / max(1, pm.train_all))
        model.reset_metrics()
    model.sync()
    assert losses[-1] < losses[0], f"MSE did not decrease: {losses[0]} -> {losses[-1]}"


@pytest.mark.slow
def test_func_mnist_mlp():
    from examples.keras.func_mnist_mlp import top_level_task

    top_level_task(num_samples=512, epochs=2)


@pytest.mark.slow
def test_func_mnist_cnn():
    from examples.keras.func_mnist_cnn import top_level_task

    top_level_task(num_samples=512, epochs=2)


@pytest.mark.slow
def test_func_mnist_cnn_concat():
    from examples.keras.func_mnist_cnn_concat import top_level_task

    top_level_task(num_samples=512, epochs=2)


@pytest.mark.slow
def test_func_mnist_mlp_concat2():
    from examples.keras.func_mnist_mlp_concat2 import top_level_task

    top_level_task(num_samples=512, epochs=4)


@pytest.mark.slow
def test_func_mnist_mlp_net2net():
    from examples.keras.func_mnist_mlp_net2net import top_level_task

    top_level_task(num_samples=512, epochs=2)


@pytest.mark.slow
def test_func_cifar10_cnn():
    from examples.keras.func_cifar10_cnn import top_level_task

    top_level_task(num_samples=512, epochs=4)


@pytest.mark.slow
def test_func_cifar10_cnn_concat():
    from examples.keras.func_cifar10_cnn_concat import top_level_task

    top_level_task(num_samples=512, epochs=4)


@pytest.mark.slow
def test_func_cifar10_alexnet():
    from examples.keras.func_cifar10_alexnet import top_level_task

    top_level_task(num_samples=512, epochs=4)


def test_unary_activations():
    from examples.keras.unary import top_level_task

    top_level_task(num_samples=512, epochs=4)


def test_callback_lr_scheduler():
    from examples.keras.callback import top_level_task

    top_level_task(num_samples=512, epochs=4)


@pytest.mark.slow
def test_seq_mnist_cnn_nested():
    from examples.keras.seq_mnist_cnn_nested import top_level_task

    top_level_task(num_samples=512, epochs=4)


@pytest.mark.slow
def test_seq_mnist_mlp_net2net():
    from examples.keras.seq_mnist_mlp_net2net import top_level_task

    top_level_task(num_samples=1024, epochs=2)


@pytest.mark.slow
def test_func_cifar10_cnn_concat_model():
    from examples.keras.func_cifar10_cnn_concat_model import top_level_task

    top_level_task(num_samples=512, epochs=4)


@pytest.mark.slow
def test_func_cifar10_cnn_concat_seq_model():
    from examples.keras.func_cifar10_cnn_concat_seq_model import top_level_task

    top_level_task(num_samples=512, epochs=4)


@pytest.mark.slow
def test_func_cifar10_cnn_nested():
    from examples.keras.func_cifar10_cnn_nested import top_level_task

    top_level_task(num_samples=512, epochs=4)


@pytest.mark.slow
def test_func_cifar10_cnn_net2net():
    from examples.keras.func_cifar10_cnn_net2net import top_level_task

    top_level_task(num_samples=512, epochs=4)


@pytest.mark.slow
def test_keras_candle_uno():
    # scaled-down towers, plus a second drug so the drug encoders are
    # genuinely SHARED across two inputs of the same feature type
    import examples.keras.candle_uno as mod

    feature_shapes = {"dose": 1, "cell.rnaseq": 64,
                      "drug.descriptors": 128, "drug.fingerprints": 96}
    input_features = {"dose1": "dose", "dose2": "dose",
                      "cell.rnaseq": "cell.rnaseq",
                      "drug1.descriptors": "drug.descriptors",
                      "drug1.fingerprints": "drug.fingerprints",
                      "drug2.descriptors": "drug.descriptors",
                      "drug2.fingerprints": "drug.fingerprints"}
    model = mod.build_model(input_features, feature_shapes,
                            [32] * 3, [32] * 3, batch_size=16)
    from flexflow_tpu.keras.optimizers import SGD

    model.compile(SGD(lr=0.001), "mean_squared_error",
                  ["mean_squared_error"])
    shared = [op for op in model.ffmodel.ops if op.share_from is not None]
    assert shared, "drug encoders should share weights across drug1/drug2"
    xs, y = mod.synthetic_data(128, input_features, feature_shapes)
    first = model.evaluate(xs, y)["mean_squared_error"]
    model.fit(xs, y, epochs=2)
    last = model.evaluate(xs, y)["mean_squared_error"]
    assert last < first
