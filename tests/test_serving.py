"""Continuous-batching serving subsystem (flexflow_tpu/serving/).

The load-bearing claim: admitting requests mid-flight into a slot-based
kv pool is TRANSPARENT — every request's greedy output is bitwise the
tokens a standalone ``FFModel.generate()`` call produces for the same
prompt, while device shapes stay static (one jitted step fn, one
prefill fn per prompt bucket — asserted via the jit-cache counters).
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.models.transformer import build_transformer
from flexflow_tpu.observability import events
from flexflow_tpu.serving import (InferenceRequest, RequestQueue,
                                  ServeConfig, ServeError, ServeTimeout)
from flexflow_tpu.serving.engine import InferenceEngine
from flexflow_tpu.tools import serve_report

V = 32          # vocab
MAX_SEQ = 64


def _make_model(seed=3):
    cfg = ff.FFConfig(batch_size=4)
    m = ff.FFModel(cfg)
    build_transformer(m, 4, seq_length=MAX_SEQ, num_layers=1,
                      embed_dim=16, num_heads=2, vocab_size=V)
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=seed)
    return m


@pytest.fixture(scope="module")
def model():
    # untrained is fine: greedy equivalence needs determinism, not
    # accuracy — and skips a training loop per module
    return _make_model()


def _prompts(n, seed=0, lo=3, hi=11):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, V, size=int(rng.integers(lo, hi + 1)))
            .astype(np.int32) for _ in range(n)]


# ---------------------------------------------------------------------------
# config / queue units
# ---------------------------------------------------------------------------

def test_serve_config_env_and_buckets(monkeypatch):
    monkeypatch.setenv("FF_SERVE_MAX_BATCH", "3")
    monkeypatch.setenv("FF_SERVE_MAX_SEQ", "48")
    monkeypatch.setenv("FF_SERVE_BUCKETS", "4,16")
    monkeypatch.setenv("FF_SERVE_QUEUE_TIMEOUT", "2.5")
    cfg = ServeConfig.from_env()
    assert (cfg.max_batch, cfg.max_seq) == (3, 48)
    assert cfg.resolved_buckets() == (4, 16)
    assert cfg.bucket_for(4) == 4 and cfg.bucket_for(5) == 16
    assert cfg.bucket_for(17) is None
    assert cfg.queue_timeout_s == 2.5
    # explicit override beats env
    assert ServeConfig.from_env(max_batch=9).max_batch == 9
    # default ladder: powers of two strictly below max_seq
    assert ServeConfig(max_seq=64).resolved_buckets() == (8, 16, 32)


def test_serve_config_rejects_bad_env(monkeypatch):
    monkeypatch.setenv("FF_SERVE_MAX_BATCH", "zero")
    with pytest.raises(ValueError, match="FF_SERVE_MAX_BATCH"):
        ServeConfig.from_env()
    monkeypatch.delenv("FF_SERVE_MAX_BATCH")
    monkeypatch.setenv("FF_SERVE_BUCKETS", "16,8")
    with pytest.raises(ValueError, match="ascending"):
        ServeConfig.from_env()
    monkeypatch.delenv("FF_SERVE_BUCKETS")
    with pytest.raises(ValueError, match="no room"):
        ServeConfig(max_seq=16, buckets=(16,))


def test_request_queue_priority_and_expiry():
    q = RequestQueue()
    a = InferenceRequest([1], 4, priority=0)
    b = InferenceRequest([1], 4, priority=5)
    c = InferenceRequest([1], 4, priority=1, timeout_s=0.0001)
    for r in (a, b, c):
        q.put(r)
    time.sleep(0.01)
    now = time.perf_counter()
    assert q.pop_ready(now) is b           # highest priority first
    assert q.expire(now) == 1              # c expired while queued
    assert c.status == "timeout"
    with pytest.raises(ServeTimeout):
        c.result(0)
    assert q.pop_ready(now) is a
    assert q.pop_ready(now) is None


# ---------------------------------------------------------------------------
# engine core
# ---------------------------------------------------------------------------

def test_greedy_equivalence_and_occupancy(model):
    """Acceptance: 8 staggered mixed-length requests, every output
    bitwise-equal to a one-shot generate() of the same prompt, and the
    continuous batch actually batched (mean occupancy > 1.5)."""
    prompts = _prompts(8, seed=1)
    news = [6, 16, 4, 12, 9, 15, 8, 10]
    eng = InferenceEngine(model, max_batch=4, max_seq=MAX_SEQ,
                          max_new_tokens=32)
    with eng:
        handles = []
        for p, n in zip(prompts, news):
            handles.append(eng.submit(p, n))
            time.sleep(0.002)              # staggered arrivals
        outs = [h.result(180) for h in handles]
    for p, n, out in zip(prompts, news, outs):
        want = model.generate(p[None], n)[0]
        assert np.array_equal(out, want), \
            f"prompt {p.tolist()}: {out.tolist()} != {want.tolist()}"
    st = eng.stats()
    assert st["completed"] == 8
    assert st["mean_occupancy"] > 1.5, st


def test_slot_reuse_after_completion(model):
    """6 requests through 2 slots: every slot is recycled mid-flight."""
    eng = InferenceEngine(model, max_batch=2, max_seq=MAX_SEQ,
                          max_new_tokens=16)
    with eng:
        hs = [eng.submit(p, 5) for p in _prompts(6, seed=2)]
        for h in hs:
            h.result(120)
    st = eng.stats()
    assert st["admitted"] == 6 and st["completed"] == 6
    assert st["max_active"] <= 2           # never more slots than pool
    assert all(s is None for s in eng._slots)


def test_bucketed_prefill_no_retrace(model):
    """Prompt lengths 3,4,5,7,8 pad into buckets {4, 8}: exactly two
    prefill compilations, and the shared step fn compiles once."""
    eng = InferenceEngine(model, max_batch=2, max_seq=MAX_SEQ,
                          buckets=(4, 8), max_new_tokens=8)
    rng = np.random.default_rng(5)
    with eng:
        hs = [eng.submit(rng.integers(0, V, size=n).astype(np.int32), 3)
              for n in (3, 4, 5, 7, 8)]
        for h in hs:
            h.result(120)
    if eng._paged:
        # paged prefill fns key on (gather-bucket, suffix-bucket); all
        # cold admissions gather nothing, so the ladder is the same
        assert sorted(eng._paged_prefill_fns) == [(0, 4), (0, 8)]
    else:
        assert sorted(eng._prefill_fns) == [4, 8]
    assert eng.stats()["prefill_compiles"] == 2


def test_queue_timeout_and_priority_order(model):
    eng = InferenceEngine(model, max_batch=1, max_seq=MAX_SEQ,
                          max_new_tokens=32)
    prompts = _prompts(4, seed=7)
    # submitted before start: admission order is purely (priority desc,
    # arrival asc) — max_batch=1 serializes it
    slow = eng.submit(prompts[0], 24, priority=10)
    low = eng.submit(prompts[1], 3, priority=0)
    high = eng.submit(prompts[2], 3, priority=5)
    doomed = eng.submit(prompts[3], 3, timeout_s=0.001)
    with eng:
        slow.result(180)
        low.result(120)
        high.result(120)
        with pytest.raises(ServeTimeout):
            doomed.result(120)
    assert doomed.status == "timeout"
    assert slow.admit_seq < high.admit_seq < low.admit_seq
    assert eng.stats()["timeouts"] == 1


def test_eos_stops_early(model):
    prompt = _prompts(1, seed=11)[0]
    want = model.generate(prompt[None], 8)[0]
    eos = int(want[2])
    stop = int(np.argmax(want == eos))     # first occurrence, inclusive
    eng = InferenceEngine(model, max_batch=1, max_seq=MAX_SEQ,
                          max_new_tokens=8)
    with eng:
        out = eng.submit(prompt, 8, eos_id=eos).result(120)
    assert np.array_equal(out, want[:stop + 1])


def test_submit_validation(model):
    eng = InferenceEngine(model, max_batch=1, max_seq=16,
                          buckets=(8,), max_new_tokens=16)
    with pytest.raises(ValueError, match="bucket"):
        eng.submit(np.arange(9, dtype=np.int32), 2)
    with pytest.raises(ValueError, match="max_seq"):
        eng.submit(np.arange(8, dtype=np.int32), 16)
    with pytest.raises(ValueError, match="exceeds the engine cap"):
        eng.submit([1, 2], 17)
    with pytest.raises(ValueError, match="empty"):
        eng.submit([], 2)


def test_engine_rejects_extra_graph_inputs():
    """A third graph input (seq2seq-style) can't be fed one token at a
    time — the engine must refuse at construction, not mis-serve."""
    m2 = ff.FFModel(ff.FFConfig(batch_size=4))
    toks = m2.create_tensor((4, 8), dtype="int32", nchw=False, name="toks")
    pos = m2.create_tensor((4, 8), dtype="int32", nchw=False, name="pos")
    seg = m2.create_tensor((4, 8), dtype="int32", nchw=False, name="seg")
    x = m2.add(m2.embedding(toks, V, 16, aggr=ff.AggrMode.NONE, name="e1"),
               m2.embedding(pos, 8, 16, aggr=ff.AggrMode.NONE, name="e2"),
               name="a1")
    x = m2.add(x, m2.embedding(seg, 4, 16, aggr=ff.AggrMode.NONE,
                               name="e3"), name="a2")
    m2.softmax(m2.dense(x, V, name="head"), name="sm")
    m2.compile(ff.SGDOptimizer(lr=0.1),
               "sparse_categorical_crossentropy", ["accuracy"])
    m2.init_layers(seed=0)
    with pytest.raises(ValueError, match="extra graph input"):
        InferenceEngine(m2, max_batch=1, max_seq=8)


def test_stop_cancels_outstanding(model):
    eng = InferenceEngine(model, max_batch=1, max_seq=MAX_SEQ,
                          max_new_tokens=32)
    eng.start()
    hs = [eng.submit(p, 24) for p in _prompts(3, seed=13)]
    hs[0].result(180)                      # first one through
    eng.stop(drain=False)
    for h in hs[1:]:
        if not h.done() or h.status != "done":
            with pytest.raises(ServeError):
                h.result(5)
    with pytest.raises(ServeError, match="not accepting"):
        eng.submit([1, 2], 2)


# ---------------------------------------------------------------------------
# chaos: per-request error isolation
# ---------------------------------------------------------------------------

def test_serve_chaos_error_isolated(monkeypatch):
    """``serve:2=error``: the second ADMITTED request fails alone — the
    loop and both neighbors are untouched (FF_CHAOS serve site)."""
    monkeypatch.setenv("FF_CHAOS", "serve:2=error")
    m = _make_model(seed=4)                # compile resolves the monkey
    assert m._chaos is not None
    eng = InferenceEngine(m, max_batch=1, max_seq=MAX_SEQ,
                          max_new_tokens=8)
    hs = [eng.submit(p, 4) for p in _prompts(3, seed=17)]
    with eng:
        out0 = hs[0].result(120)
        with pytest.raises(ServeError, match="ChaosError"):
            hs[1].result(120)
        out2 = hs[2].result(120)
    assert hs[1].status == "error"
    assert ("serve", 2, "error") in m._chaos.fired
    assert np.array_equal(out0, m.generate(hs[0].prompt[None], 4)[0])
    assert np.array_equal(out2, m.generate(hs[2].prompt[None], 4)[0])
    st = eng.stats()
    assert st["completed"] == 2 and st["failed"] == 1


# ---------------------------------------------------------------------------
# HTTP front end + serve_report
# ---------------------------------------------------------------------------

def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def test_http_roundtrip_ephemeral_port(model, tmp_path):
    from flexflow_tpu.serving.api import ServingAPI

    log = events.EventLog(str(tmp_path / "serve.jsonl"))
    eng = InferenceEngine(model, max_batch=2, max_seq=MAX_SEQ,
                          max_new_tokens=16, telemetry=log)
    prompt = _prompts(1, seed=19)[0]
    with eng, ServingAPI(eng, port=0) as api:
        out = _post(f"{api.url}/generate",
                    {"prompt": [int(t) for t in prompt],
                     "max_new_tokens": 6})
        assert np.array_equal(np.asarray(out["tokens"], np.int32),
                              model.generate(prompt[None], 6)[0])
        assert out["prompt_len"] == prompt.size
        assert out["ttft_s"] > 0
        # health endpoint reflects live engine state
        with urllib.request.urlopen(f"{api.url}/healthz", timeout=30) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok" and health["completed"] >= 1
        # malformed body / sampling knob / unknown path -> 4xx
        for payload, code in ((
                {"max_new_tokens": 4}, 400),           # no prompt
                ({"prompt": [1], "temperature": 0.7}, 400)):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"{api.url}/generate", payload)
            assert ei.value.code == code
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{api.url}/nope", timeout=30)
        assert ei.value.code == 404
    log.close()

    # the trace the round-trip produced folds into a serving report
    report = serve_report.main([str(tmp_path / "serve.jsonl"),
                                "-o", str(tmp_path / "r.md")])
    assert "## Latency (ms)" in report
    assert "| queue wait |" in report and "| TTFT |" in report
    assert "## Batch occupancy" in report
    assert "| done | 1 |" in report


def test_serve_report_empty_trace(tmp_path):
    p = tmp_path / "empty.jsonl"
    p.write_text('{"t": "meta", "run_id": "x", "pid": 1}\n')
    assert "no serving records" in serve_report.main([str(p)])
